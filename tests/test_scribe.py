"""Scribe service: summarize -> ack -> boot-from-summary -> compaction.

Pins the tentpole contract of server/scribe.py over server/ordered_log.py
and server/gitstore.py:

- a cold consumer booting from the latest ACKED summary commit plus the
  post-ack tail reaches byte-identical state to a full-history replay, for
  all four engine families (string / tree / map / matrix);
- log compaction never truncates past the minimum acked/committed offset
  across the consumer group, and a consumer whose committed offset falls
  below the truncated floor resumes at the floor (counted, not raised);
- a scribe crash/restart (even with lost consumer offsets) replays its own
  acks from the ordered log and never double-acks a summary it already
  produced.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from fluidframework_tpu.protocol.messages import (
    DeltaType,
    MessageType,
    SequencedMessage,
)
from fluidframework_tpu.runtime.summary import parse_scribe_ack
from fluidframework_tpu.server.ordered_log import ConsumerGroup, DurableTopic, Topic
from fluidframework_tpu.server.scribe import (
    ScribeConfig,
    ScribeLambda,
    SummaryRecordStore,
    detect_family,
)


# ------------------------------------------------------------------ helpers

def _join(doc, topic, client="w0", short=0):
    topic.produce(doc, SequencedMessage(
        seq=0, min_seq=0, ref_seq=0, client_id=client, client_seq=0,
        type=MessageType.JOIN, contents={"clientId": client, "short": short},
    ))


def _op(doc, topic, seq, contents, client="w0", ref=0, min_seq=0):
    msg = SequencedMessage(
        seq=seq, min_seq=min_seq, ref_seq=ref, client_id=client,
        client_seq=seq, type=MessageType.OP, contents=contents,
    )
    topic.produce(doc, msg)
    return msg


def _durable_topic(tmp_path, n_partitions=1):
    return DurableTopic(
        "deltas", n_partitions, str(tmp_path / "log"),
        encode=lambda m: m.to_json(), decode=SequencedMessage.from_json,
    )


def _string_stream(doc, topic, seqs, seed=0):
    """Deterministic single-writer string edits (valid in own perspective)."""
    rng = np.random.default_rng(seed)
    length = 0
    out = []
    for s in seqs:
        if length >= 4 and rng.random() < 0.3:
            p = int(rng.integers(0, length - 1))
            out.append(_op(doc, topic, s, {"type": 1, "pos1": p, "pos2": p + 1}))
            length -= 1
        else:
            p = int(rng.integers(0, length + 1))
            out.append(_op(doc, topic, s, {"type": 0, "pos1": p, "seg": "ab"}))
            length += 2
    return out


def _acks_for(topic, doc):
    out = []
    for p in range(topic.n_partitions):
        for rec in topic.partition(p).read(0):
            ack = parse_scribe_ack(rec.payload)
            if ack is not None and ack[0] == doc:
                out.append(ack)
    return out


# --------------------------------------------------- boot-from-summary: string

def test_boot_from_summary_string_byte_identity(tmp_path):
    from fluidframework_tpu.models.doc_batch_engine import DocBatchEngine

    topic = _durable_topic(tmp_path)
    _join("d0", topic)
    msgs = list(_string_stream("d0", topic, range(1, 25)))
    scribe = ScribeLambda(topic, str(tmp_path / "scribe"),
                          config=ScribeConfig(max_ops=10))
    scribe.pump()
    assert scribe.health()["summaries_written"] >= 1
    # The ack rides the ordered log, after the ops it covers.
    (doc, seq, commit), = _acks_for(topic, "d0")[-1:]
    assert doc == "d0" and seq == 24 and commit in scribe.store
    # Post-ack tail.
    msgs += _string_stream("d0", topic, range(25, 31), seed=9)
    all_msgs = [m for m in msgs]

    def feed(eng):
        eng.ingest(0, SequencedMessage(
            seq=0, min_seq=0, ref_seq=0, client_id="w0", client_seq=0,
            type=MessageType.JOIN, contents={"clientId": "w0", "short": 0}))
        for m in all_msgs:
            eng.ingest(0, m)
        eng.step()

    full = DocBatchEngine(1, max_insert_len=8, ops_per_step=4, use_mesh=False,
                          doc_keys=["d0"])
    feed(full)

    boot = DocBatchEngine(1, max_insert_len=8, ops_per_step=4, use_mesh=False,
                          doc_keys=["d0"])
    store = SummaryRecordStore.from_scribe(scribe)
    assert boot.restore_from_checkpoints(store=store) == [0]
    feed(boot)  # full stream from offset 0: covered prefix must skip

    assert boot.text(0) == full.text(0)
    assert boot.annotations(0) == full.annotations(0)
    h = boot.health()
    assert h["checkpointed_ops_skipped"] == 24  # the acked prefix
    assert h["boot_replay_len"] == 6            # only the post-ack tail
    assert not boot.errors().any()
    topic.close()
    scribe.close()


# ----------------------------------------------------- boot-from-summary: tree

def test_boot_from_summary_tree_byte_identity(tmp_path):
    from test_tree_batch_engine import drive_tree_docs

    from fluidframework_tpu.models.tree_batch_engine import TreeBatchEngine

    svc, expected = drive_tree_docs(2, seed=4, steps=16)
    topic = Topic("deltas", 1)
    streams = {d: list(svc.document(f"doc{d}").sequencer.log) for d in range(2)}
    # Ship a PREFIX through the scribe; the rest is the post-ack tail.
    cut = {d: (2 * len(streams[d])) // 3 for d in streams}
    for d, msgs in streams.items():
        for m in msgs[: cut[d]]:
            topic.produce(f"doc{d}", m)
    scribe = ScribeLambda(topic, str(tmp_path / "scribe"),
                          config=ScribeConfig(max_ops=4))
    scribe.pump()
    assert scribe.health()["summaries_written"] >= 2
    for d, msgs in streams.items():
        for m in msgs[cut[d]:]:
            topic.produce(f"doc{d}", m)

    full = TreeBatchEngine(2, doc_keys=["doc0", "doc1"])
    for d, msgs in streams.items():
        for m in msgs:
            full.ingest(d, m)
    full.step()

    boot = TreeBatchEngine(2, doc_keys=["doc0", "doc1"])
    restored = boot.restore_from_checkpoints(
        store=SummaryRecordStore.from_scribe(scribe)
    )
    assert restored == [0, 1]
    boot.step()  # apply the re-materialization rows
    for d, msgs in streams.items():  # full stream: covered prefix skips
        for m in msgs:
            boot.ingest(d, m)
    boot.step()
    for d in range(2):
        assert boot.values(d) == full.values(d) == expected[d], f"doc {d}"
    h = boot.health()
    assert h["checkpointed_ops_skipped"] > 0 and h["boot_replay_len"] > 0
    scribe.close()


# ----------------------------------------------- boot-from-summary: map/matrix

def test_boot_from_summary_map_and_matrix_byte_identity(tmp_path):
    import jax

    from fluidframework_tpu.server.scribe import _MapDocScribe, _MatrixDocScribe

    topic = _durable_topic(tmp_path)
    rng = np.random.default_rng(1)
    map_msgs, mx_msgs = [], []
    # Map traffic: sets/deletes/clears over a small key space.
    for s in range(1, 31):
        r = rng.random()
        if r < 0.7:
            c = {"type": "set", "key": f"k{int(rng.integers(6))}",
                 "value": int(rng.integers(100))}
        elif r < 0.9:
            c = {"type": "delete", "key": f"k{int(rng.integers(6))}"}
        else:
            c = {"type": "clear"}
        map_msgs.append(_op("dmap", topic, s, c))
    # Matrix traffic: structure from one writer, then a cell storm.
    _join("dmx", topic)
    mx_msgs.append(_op("dmx", topic, 1, {"type": "insertRows", "pos": 0, "count": 4}))
    mx_msgs.append(_op("dmx", topic, 2, {"type": "insertCols", "pos": 0, "count": 4},
                       ref=1))
    for s in range(3, 27):
        mx_msgs.append(_op("dmx", topic, s, {
            "type": "set", "row": int(rng.integers(4)),
            "col": int(rng.integers(4)), "value": int(rng.integers(50)),
        }, ref=2))

    scribe = ScribeLambda(topic, str(tmp_path / "scribe"),
                          config=ScribeConfig(max_ops=12, map_max_keys=16,
                                              matrix_shape=(8, 8),
                                              matrix_segments=16))
    scribe.pump()
    store = SummaryRecordStore.from_scribe(scribe)
    rec_map, rec_mx = store.load("dmap"), store.load("dmx")
    assert rec_map["engine"] == "map_batch" and rec_mx["engine"] == "matrix_batch"
    assert store.family("dmap") == "map_batch"

    # Post-ack tails.
    for s in range(31, 37):
        map_msgs.append(_op("dmap", topic, s, {
            "type": "set", "key": f"k{int(rng.integers(6))}",
            "value": int(rng.integers(100))}))
    for s in range(27, 33):
        mx_msgs.append(_op("dmx", topic, s, {
            "type": "set", "row": int(rng.integers(4)),
            "col": int(rng.integers(4)), "value": int(rng.integers(50)),
        }, ref=2))

    # Full replay vs boot-from-summary + tail, byte-identical state arrays.
    full_map = _MapDocScribe(max_keys=16)
    for m in map_msgs:
        full_map.apply(m)
    full_map.flush()
    boot_map = _MapDocScribe(max_keys=16)
    boot_map.load(rec_map["seq"], rec_map)
    for m in map_msgs:
        boot_map.apply(m)  # covered prefix skips by seq floor
    boot_map.flush()
    for a, b in zip(jax.tree.leaves(full_map.state), jax.tree.leaves(boot_map.state)):
        assert (np.asarray(a) == np.asarray(b)).all()
    assert full_map.items() == boot_map.items()

    full_mx = _MatrixDocScribe(shape=(8, 8), segments=16)
    full_mx.quorum = {"w0": 0}
    for m in mx_msgs:
        full_mx.apply(m)
    full_mx.flush()
    boot_mx = _MatrixDocScribe(shape=(8, 8), segments=16)
    boot_mx.load(rec_mx["seq"], rec_mx)
    for m in mx_msgs:
        boot_mx.apply(m)
    boot_mx.flush()
    for a, b in zip(jax.tree.leaves(full_mx.state), jax.tree.leaves(boot_mx.state)):
        assert (np.asarray(a) == np.asarray(b)).all()
    assert full_mx.grid() == boot_mx.grid()
    assert not int(full_mx.state.error) and not int(boot_mx.state.error)
    topic.close()
    scribe.close()


# ------------------------------------------------------------------ compaction

def test_compaction_never_passes_min_acked_or_committed(tmp_path):
    topic = _durable_topic(tmp_path)
    _join("d0", topic)
    _string_stream("d0", topic, range(1, 31))
    scribe = ScribeLambda(topic, str(tmp_path / "scribe"),
                          config=ScribeConfig(max_ops=10))
    scribe.pump()

    # A fleet consumer group lagging mid-log pins the floor.
    fleet = ConsumerGroup(topic, "fleet", str(tmp_path / "scribe"))
    fleet.join("f0")
    recs = fleet.consume("f0")
    lag_at = recs[14][1].offset + 1
    fleet.commit(0, lag_at)

    stats = scribe.compact(extra_groups=(fleet,))
    part = topic.partition(0)
    assert part.base <= min(lag_at, scribe.refs["d0"]["offset"])
    assert part.base == min(lag_at, scribe.refs["d0"]["offset"])
    assert stats["records"] == part.base and stats["bytes"] > 0

    # The lagging consumer resumes exactly where it committed: no skips,
    # no divergence, offsets still absolute.
    tail = fleet.consume("f0")
    assert fleet.truncated_records_skipped == 0
    assert [r.offset for _p, r in tail] == list(range(lag_at, part.head))

    # As the group catches up + the scribe acks more, the floor advances
    # under sustained traffic — disk stays bounded.
    for _p, r in tail:
        fleet.commit(0, r.offset + 1)
    _string_stream("d0", topic, range(31, 61), seed=7)
    scribe.pump()
    for p, r in fleet.consume("f0"):
        fleet.commit(p, r.offset + 1)
    base_before = part.base
    scribe.compact(extra_groups=(fleet,))
    assert part.base > base_before
    assert scribe.health()["log_bytes_reclaimed"] > 0

    # Durability: reopening the topic preserves the floor and the tail.
    topic.close()
    topic2 = _durable_topic(tmp_path)
    topic2.open_all()
    p2 = topic2.partition(0)
    assert p2.base == part.base and p2.head == part.head
    assert [r.offset for r in p2.read(0)] == list(range(p2.base, p2.head))
    topic2.close()
    scribe.close()


def test_consumer_below_floor_resumes_at_floor_with_telemetry(tmp_path):
    topic = _durable_topic(tmp_path)
    _join("d0", topic)
    _string_stream("d0", topic, range(1, 21))
    scribe = ScribeLambda(topic, str(tmp_path / "scribe"),
                          config=ScribeConfig(max_ops=5))
    scribe.pump()
    scribe.compact()  # only the scribe group: floor = its acked offset
    part = topic.partition(0)
    assert part.base > 0

    # A group that was NOT part of the retention policy (committed offset
    # 0, below the floor) must resume at the floor and count the gap.
    late = ConsumerGroup(topic, "late-fleet")
    late.join("m0")
    assert late.committed(0) == part.base
    recs = late.consume("m0")
    assert late.truncated_records_skipped == part.base
    assert [r.offset for _p, r in recs] == list(range(part.base, part.head))
    # Counted once, not per pump.
    late.consume("m0")
    assert late.truncated_records_skipped == part.base
    topic.close()
    scribe.close()


# ------------------------------------------------------------- crash/restart

def test_scribe_restart_never_double_acks(tmp_path):
    topic = _durable_topic(tmp_path)
    _join("d0", topic)
    _string_stream("d0", topic, range(1, 25))
    sdir = str(tmp_path / "scribe")
    scribe = ScribeLambda(topic, sdir, config=ScribeConfig(max_ops=10))
    scribe.pump()
    assert len(_acks_for(topic, "d0")) == 1
    refs_before = dict(scribe.refs)
    scribe.close()

    # Crash that LOSES the committed consumer offsets (the worst case:
    # the ack reached the log but the offset commit did not).
    os.remove(os.path.join(sdir, "offsets-scribe.json"))
    scribe2 = ScribeLambda(topic, sdir, config=ScribeConfig(max_ops=10))
    assert scribe2.health()["docs_restored"] == 1
    scribe2.pump()  # replays the full log INCLUDING its own ack
    # No duplicate ack, no second summary, refs unchanged.
    assert len(_acks_for(topic, "d0")) == 1
    assert scribe2.health().get("summaries_written", 0) == 0
    assert scribe2.refs["d0"]["commit"] == refs_before["d0"]["commit"]

    # New traffic after the restart summarizes normally (exactly one new
    # ack) and the chain links to the pre-crash commit.
    _string_stream("d0", topic, range(25, 41), seed=3)
    scribe2.pump()
    acks = _acks_for(topic, "d0")
    assert len(acks) == 2 and acks[-1][1] == 40
    _k, payload = scribe2.store.get(acks[-1][2])
    assert payload["parent"] == refs_before["d0"]["commit"]
    # Handle reuse: the quorum channel was untouched between the commits.
    assert scribe2.health()["summary_handles_reused"] >= 1
    topic.close()
    scribe2.close()


def test_scribe_crash_cannot_lose_folded_unsummarized_ops(tmp_path):
    """The durable group offset only ever commits up to the COVERED floor:
    ops folded into the in-memory replica but not yet inside an acked
    summary are re-read after a crash — the next summary misses nothing."""
    topic = _durable_topic(tmp_path)
    _join("d0", topic)
    sdir = str(tmp_path / "scribe")
    scribe = ScribeLambda(topic, sdir, config=ScribeConfig(max_ops=10))
    _string_stream("d0", topic, range(1, 11))
    scribe.pump()  # due -> summary + ack at seq 10
    assert scribe.refs["d0"]["seq"] == 10
    tail = _string_stream("d0", topic, range(11, 16), seed=5)
    scribe.pump()  # folded but NOT due: no summary cut
    # The commit floor pins at the first uncovered op (join + 10 ops + the
    # ack record precede it), even though the read position is at head.
    part = topic.partition(0)
    assert scribe.group.committed(0) == part.head - len(tail)
    scribe.close()  # crash: the in-memory fold of ops 11-15 dies

    scribe2 = ScribeLambda(topic, sdir, config=ScribeConfig(max_ops=10))
    _string_stream("d0", topic, range(16, 21), seed=6)
    scribe2.pump()  # re-reads 11-15 from the log, then 16-20 -> due
    assert scribe2.refs["d0"]["seq"] == 20
    rec = SummaryRecordStore.from_scribe(scribe2).load("d0")
    # Replay the acked summary through the engine restore path and check
    # it reflects EVERY op, including the 5 that died with the crash.
    from fluidframework_tpu.models.doc_batch_engine import DocBatchEngine

    eng = DocBatchEngine(1, max_insert_len=8, ops_per_step=4, use_mesh=False,
                         doc_keys=["d0"])
    eng.restore_from_checkpoints(store=SummaryRecordStore.from_scribe(scribe2))
    ctl = DocBatchEngine(1, max_insert_len=8, ops_per_step=4, use_mesh=False,
                         doc_keys=["d0"])
    for p in range(topic.n_partitions):
        for r in topic.partition(p).read(0):
            if isinstance(r.payload, SequencedMessage):
                ctl.ingest(0, r.payload)
    ctl.step()
    assert eng.text(0) == ctl.text(0)
    topic.close()
    scribe2.close()


def test_scribe_failed_doc_is_isolated(tmp_path):
    """A doc whose stream the scribe cannot apply (unknown client) is
    marked failed and never summarized; sibling docs keep summarizing."""
    topic = _durable_topic(tmp_path)
    _join("good", topic)
    _string_stream("good", topic, range(1, 13))
    _op("bad", topic, 1, {"type": 0, "pos1": 0, "seg": "x"}, client="ghost")
    scribe = ScribeLambda(topic, str(tmp_path / "scribe"),
                          config=ScribeConfig(max_ops=5))
    scribe.pump()
    h = scribe.health()
    assert h["failed_docs"] == 1 and h["docs_failed"] == 1
    assert "good" in scribe.refs and "bad" not in scribe.refs
    topic.close()
    scribe.close()


# ------------------------------------------------- multi-scribe rebalance

def test_multi_scribe_rebalance_kill_midstream(tmp_path):
    """Scribe scale-out (ROADMAP): two pool members share one topic via
    the group in ``partition_manager.ScribePool``; killing one mid-stream
    (folded-but-unsummarized work lost, no flush) rebalances its
    partitions to the survivor, which resumes every doc by summary
    adoption — no doc is double-acked, every partition's summary chain
    continues from the pre-kill commit, and boot-from-summary stays
    byte-identical to full replay."""
    from fluidframework_tpu.models.doc_batch_engine import DocBatchEngine
    from fluidframework_tpu.server.partition_manager import ScribePool

    topic = _durable_topic(tmp_path, n_partitions=4)
    docs = [f"d{i}" for i in range(4)]  # byte-sum routing: d<i> -> partition i
    for d in docs:
        _join(d, topic)
    pool = ScribePool(topic, str(tmp_path / "scribe"),
                      config=ScribeConfig(max_ops=10))
    a = pool.add_member("a")
    b = pool.add_member("b")
    owned = {p for m in ("a", "b") for p in pool.group.assignments(m)}
    assert owned == {0, 1, 2, 3}
    assert pool.group.assignments("a") and pool.group.assignments("b")

    # Phase 1: every doc summarizes once (14 ops > max_ops).
    for i, d in enumerate(docs):
        _string_stream(d, topic, range(1, 15), seed=i)
    pool.pump()
    first = {}
    for d in docs:
        acks = _acks_for(topic, d)
        assert len(acks) == 1 and acks[0][1] == 14
        first[d] = acks[0][2]

    # Phase 2: fold-but-not-due traffic, then KILL member a mid-stream —
    # its in-memory fold of these 5 ops dies unsummarized.
    for i, d in enumerate(docs):
        _string_stream(d, topic, range(15, 20), seed=10 + i)
    pool.pump()
    killed_partitions = pool.group.assignments("a")
    pool.kill_member("a")
    assert pool.group.assignments("b") == [0, 1, 2, 3]

    # Phase 3: traffic continues; the survivor re-reads the dead member's
    # uncovered tail from the group floor, folds onto ADOPTED summaries,
    # and cuts exactly one new ack per doc.
    for i, d in enumerate(docs):
        _string_stream(d, topic, range(20, 30), seed=20 + i)
    pool.pump()
    for d in docs:
        acks = _acks_for(topic, d)
        seqs = [s for _d, s, _c in acks]
        assert len(acks) == 2, f"{d}: expected exactly 2 acks, got {seqs}"
        assert len(set(seqs)) == len(seqs) and seqs == sorted(seqs)
        assert acks[-1][1] == 29
        # The post-kill chain links to the pre-kill commit: the survivor
        # adopted the dead member's summary, it did not restart from zero.
        _k, payload = pool.store.get(acks[-1][2])
        assert payload["parent"] == first[d]
    # The survivor adopted exactly the dead member's docs.
    assert b.health()["summaries_adopted"] == len(killed_partitions)
    # Idempotence: re-pumping (which drains phase 3's own ack records)
    # never re-acks or re-summarizes.
    pool.pump()
    pool.pump()
    for d in docs:
        assert len(_acks_for(topic, d)) == 2

    # Boot-from-summary through the survivor's record store is
    # byte-identical to a full-history replay for EVERY doc, including the
    # dead member's.
    store = SummaryRecordStore.from_scribe(b)
    eng = DocBatchEngine(4, max_insert_len=8, ops_per_step=4, use_mesh=False,
                         doc_keys=docs)
    eng.restore_from_checkpoints(store=store)
    ctl = DocBatchEngine(4, max_insert_len=8, ops_per_step=4, use_mesh=False,
                         doc_keys=docs)
    by_doc = {d: i for i, d in enumerate(docs)}
    for p in range(topic.n_partitions):
        for r in topic.partition(p).read(0):
            if isinstance(r.payload, SequencedMessage) and r.doc_id in by_doc:
                ctl.ingest(by_doc[r.doc_id], r.payload)
    ctl.step()
    for i, d in enumerate(docs):
        assert eng.text(i) == ctl.text(i), d

    # Pool-safe compaction reclaims the covered prefix without stranding
    # any partition (refs union pins the floors).
    reclaimed = pool.compact()
    assert sum(reclaimed.values()) >= 0
    topic.close()
    pool.close()


# ---------------------------------------------------------------- detection

def test_stale_restored_replica_readopts_on_partition_gain(tmp_path):
    """The r10 chaos-soak regression: a member restored long ago holds an
    in-memory replica at an OLD summary; a peer then advances the doc
    (new joins + ops, fresh acked summaries, committed floor moves past
    those records).  When the stale member later GAINS the partition
    (peer killed), folding the tail onto its stale state would gap the
    replica (quorum KeyErrors, corrupt summaries) because the missing
    records sit below the committed floor and are never re-read.  The
    owner must instead drop the stale replica and re-adopt the persisted
    acked summary."""
    from fluidframework_tpu.dds.mergetree_ref import RefMergeTree
    from fluidframework_tpu.server.partition_manager import ScribePool

    topic = _durable_topic(tmp_path)
    pool = ScribePool(topic, str(tmp_path / "scribe"),
                      config=ScribeConfig(max_ops=10))
    a = pool.add_member("a")

    def seg(s):
        return chr(65 + s % 26) + chr(97 + s % 26)

    _join("d0", topic, client="w0", short=0)
    all_ops = []
    for s in range(1, 15):
        all_ops.append(_op("d0", topic, s, {"type": 0, "pos1": 0,
                                            "seg": seg(s)}))
    a.pump()  # summary + ack at 14 -> refs.json written
    assert _acks_for(topic, "d0") == [("d0", 14, _acks_for(topic, "d0")[0][2])]

    # Member b restores NOW: replica at seq 14.  One partition, dealt to
    # "a" (first in sorted membership) — b idles while a advances the doc.
    b = pool.add_member("b")
    assert b.docs["d0"].last_seq == 14
    assert pool.group.assignments("b") == []
    _join("d0", topic, client="w1", short=1)  # a NEW client b never sees
    for s in range(15, 31):
        all_ops.append(_op("d0", topic, s, {"type": 0, "pos1": 0,
                                            "seg": seg(s)}, client="w1"))
    pool.pump()  # a folds + summarizes at 30; committed floor passes it
    assert [s for _d, s, _c in _acks_for(topic, "d0")] == [14, 30]
    assert b.docs["d0"].last_seq == 14  # still stale in memory

    # The stale member takes over: it must re-adopt, not fold onto 14.
    pool.kill_member("a")
    for s in range(31, 36):
        all_ops.append(_op("d0", topic, s, {"type": 0, "pos1": 0,
                                            "seg": seg(s)}, client="w1"))
    pool.pump()
    assert b.counters.get("stale_replicas_dropped") == 1
    ad = b.docs["d0"]
    assert ad.failed is None
    assert ad.base_seq == 30 and ad.last_seq == 35

    # Byte identity against a fault-free oracle replay of the full log.
    oracle = RefMergeTree()
    for i, m in enumerate(all_ops):
        oracle.apply_insert(m.contents["pos1"], m.contents["seg"], m.seq,
                            0 if m.client_id == "w0" else 1, m.ref_seq)
    assert ad.tree.visible_text() == oracle.visible_text()
    # And the successor's next summary chains cleanly (no double-acks).
    assert b.summarize("d0") is not None
    assert [s for _d, s, _c in _acks_for(topic, "d0")] == [14, 30, 35]


def test_family_detection():
    assert detect_family({"type": 0, "pos1": 0, "seg": "x"}) == "doc_batch"
    assert detect_family({"type": "set", "key": "k", "value": 1}) == "map_batch"
    assert detect_family({"type": "clear"}) == "map_batch"
    assert detect_family({"type": "set", "row": 1, "col": 2, "value": 3}) == "matrix_batch"
    assert detect_family({"type": "insertRows", "pos": 0, "count": 1}) == "matrix_batch"
    assert detect_family({"type": "edit", "sid": "s", "rev": 1, "changes": []}) == "tree_batch"
    assert detect_family({"address": "root", "contents": {}}) == "tree_batch"


# ------------------------------------------------------------------- tooling

def test_summary_inspect_cli(tmp_path, capsys):
    from fluidframework_tpu.tools.summary_inspect import main as inspect_main

    topic = _durable_topic(tmp_path)
    _join("d0", topic)
    _string_stream("d0", topic, range(1, 13))
    sdir = str(tmp_path / "scribe")
    scribe = ScribeLambda(topic, sdir, config=ScribeConfig(max_ops=6))
    scribe.pump()
    _string_stream("d0", topic, range(13, 25), seed=2)
    scribe.pump()
    assert len(_acks_for(topic, "d0")) == 2
    scribe.close()

    assert inspect_main(["list", sdir]) == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()]
    assert len(lines) == 2 and {l["seq"] for l in lines} == {12, 24}
    assert sum(l["latest"] for l in lines) == 1

    assert inspect_main(["show", sdir, "--doc", "d0"]) == 0
    shown = json.loads(capsys.readouterr().out)
    assert shown["seq"] == 24 and shown["record"]["engine"] == "doc_batch"

    assert inspect_main(["diff", sdir, "--doc", "d0"]) == 0
    diff = json.loads(capsys.readouterr().out)
    assert diff["from"]["seq"] == 12 and diff["to"]["seq"] == 24
    assert any(c["path"].startswith("summary") for c in diff["changes"])
    topic.close()
