"""SharedTree branch API (fork/rebase/merge) and schema evolution.

Mirrors the reference's branch suites (tree/src/test/shared-tree-core/
branch.spec.ts, simple-tree branch tests) and the schematize/compatibility
suites (shared-tree/schematizingTreeView.spec.ts: canView/canUpgrade/
upgradeSchema)."""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.dds.tree.changeset import (
    make_insert,
    make_remove,
    make_set_value,
)
from fluidframework_tpu.dds.tree.schema import (
    FieldKind,
    FieldSchema,
    NodeSchema,
    SchemaRegistry,
    array_schema,
    leaf,
    schema_compat,
)
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService


def make_container(doc, name: str) -> ContainerRuntime:
    c = ContainerRuntime(default_registry(), container_id=name)
    ds = c.create_datastore("root")
    ds.create_channel("sharedTree", "tree")
    c.connect(doc, name)
    return c


def tree_of(c):
    return c.datastore("root").get_channel("tree")


def root_values(t) -> list:
    return [n.value for n in t.forest.root_field]


def setup_pair():
    svc = LocalService()
    doc = svc.document("d1")
    a = make_container(doc, "A")
    b = make_container(doc, "B")
    doc.process_all()
    return svc, doc, a, b


def ins(i, v):
    return make_insert([], "", i, [leaf(v)])


# --------------------------------------------------------------------------
# branches
# --------------------------------------------------------------------------

def test_branch_edits_stay_local_until_merge():
    svc, doc, a, b = setup_pair()
    ta = tree_of(a)
    ta.submit_change(ins(0, 1))
    a.flush(); doc.process_all()
    br = ta.fork()
    br.submit_change(ins(1, 2))
    br.submit_change(ins(2, 3))
    assert [n.value for n in br.forest.root_field] == [1, 2, 3]
    assert root_values(ta) == [1]  # parent untouched
    a.flush(); doc.process_all()
    assert root_values(tree_of(b)) == [1]  # nothing shipped
    br.merge_into_parent()
    assert root_values(ta) == [1, 2, 3]
    a.flush(); doc.process_all()
    assert root_values(tree_of(b)) == [1, 2, 3]
    assert br.disposed
    with pytest.raises(RuntimeError):
        br.submit_change(ins(0, 9))


def test_branch_rebase_onto_parent_picks_up_remote_edits():
    svc, doc, a, b = setup_pair()
    ta, tb = tree_of(a), tree_of(b)
    ta.submit_change(ins(0, 10))
    a.flush(); doc.process_all()
    br = ta.fork()
    br.submit_change(ins(1, 20))       # branch: [10, 20]
    tb.submit_change(ins(0, 5))        # B concurrently prepends
    b.flush(); doc.process_all()
    assert root_values(ta) == [5, 10]
    assert [n.value for n in br.forest.root_field] == [10, 20]  # not yet
    br.rebase_onto_parent()
    assert [n.value for n in br.forest.root_field] == [5, 10, 20]
    br.merge_into_parent()
    a.flush(); doc.process_all()
    assert root_values(ta) == root_values(tb) == [5, 10, 20]


def test_branch_merge_is_atomic_on_the_wire():
    svc, doc, a, b = setup_pair()
    ta = tree_of(a)
    n_before = len(tree_of(b).em.trunk)
    br = ta.fork()
    for i, v in enumerate([1, 2, 3]):
        br.submit_change(ins(i, v))
    br.merge_into_parent()
    a.flush()
    doc.process_all()
    assert root_values(tree_of(b)) == [1, 2, 3]
    # One trunk commit (one transaction on the wire), not three.
    assert len(tree_of(b).em.trunk) == n_before + 1


def test_nested_branches():
    svc, doc, a, b = setup_pair()
    ta = tree_of(a)
    ta.submit_change(ins(0, 1))
    br = ta.fork()
    br.submit_change(ins(1, 2))
    grand = br.fork()
    grand.submit_change(ins(2, 3))
    assert [n.value for n in grand.forest.root_field] == [1, 2, 3]
    br.submit_change(ins(0, 0))        # branch diverges under grandchild
    grand.rebase_onto_parent()
    assert [n.value for n in grand.forest.root_field] == [0, 1, 2, 3]
    grand.merge_into_parent()
    assert [n.value for n in br.forest.root_field] == [0, 1, 2, 3]
    br.merge_into_parent()
    a.flush(); doc.process_all()
    assert root_values(ta) == root_values(tree_of(b)) == [0, 1, 2, 3]


def test_nested_branch_view_resolves_document_schema():
    svc, doc, a, b = setup_pair()
    ta = tree_of(a)
    ta.set_schema(reg_v1())
    a.flush(); doc.process_all()
    br = ta.fork()
    grand = br.fork()
    assert grand.view.registry is not None
    assert grand.view.registry.to_json() == reg_v1().to_json()


def test_branch_transaction_and_abort():
    svc, doc, a, b = setup_pair()
    ta = tree_of(a)
    br = ta.fork()
    with br.transaction():
        br.submit_change(ins(0, 1))
        br.submit_change(ins(1, 2))
    with pytest.raises(ValueError):
        with br.transaction():
            br.submit_change(ins(2, 3))
            raise ValueError("abort")
    assert [n.value for n in br.forest.root_field] == [1, 2]
    br.merge_into_parent()
    a.flush(); doc.process_all()
    assert root_values(tree_of(b)) == [1, 2]


def test_failed_merge_keeps_branch_intact_for_retry():
    svc, doc, a, b = setup_pair()
    ta = tree_of(a)
    br = ta.fork()
    br.submit_change(ins(0, 7))
    with pytest.raises(RuntimeError):
        with ta.transaction():
            ta.submit_change(ins(0, 1))
            br.merge_into_parent()  # parent txn open: nested txn raises
    assert not br.disposed and br.has_changes
    br.merge_into_parent()  # retry succeeds
    a.flush(); doc.process_all()
    assert 7 in root_values(tree_of(b))


def test_concurrent_branch_merges_converge():
    svc, doc, a, b = setup_pair()
    ta, tb = tree_of(a), tree_of(b)
    ta.submit_change(ins(0, 100))
    a.flush(); doc.process_all()
    ba = ta.fork(); ba.submit_change(ins(1, 1))
    bb = tb.fork(); bb.submit_change(ins(1, 2))
    ba.merge_into_parent()
    bb.merge_into_parent()
    a.flush(); b.flush(); doc.process_all()
    assert root_values(ta) == root_values(tb)
    assert sorted(root_values(ta)) == [1, 2, 100]


# --------------------------------------------------------------------------
# schema evolution
# --------------------------------------------------------------------------

def reg_v1() -> SchemaRegistry:
    r = SchemaRegistry()
    r.add(array_schema("list", {"number"}))
    r.root = FieldSchema(FieldKind.VALUE, {"list"})
    return r


def reg_widened() -> SchemaRegistry:
    r = SchemaRegistry()
    r.add(array_schema("list", {"number", "string"}))  # widened items
    r.root = FieldSchema(FieldKind.VALUE, {"list"})
    return r


def reg_new_required_field() -> SchemaRegistry:
    r = SchemaRegistry()
    s = array_schema("list", {"number"})
    s.fields["meta"] = FieldSchema(FieldKind.VALUE, {"string"})
    r.add(s)
    r.root = FieldSchema(FieldKind.VALUE, {"list"})
    return r


def reg_new_optional_field() -> SchemaRegistry:
    r = SchemaRegistry()
    s = array_schema("list", {"number"})
    s.fields["meta"] = FieldSchema(FieldKind.OPTIONAL, {"string"})
    r.add(s)
    r.root = FieldSchema(FieldKind.VALUE, {"list"})
    return r


def test_schema_compat_rules():
    c = schema_compat(reg_v1(), reg_v1())
    assert c.is_equivalent and c.can_view and c.can_upgrade
    # widening allowed types: upgrade only — viewing would let this client
    # write strings the stored schema forbids (canView is no-upgrade compat)
    c = schema_compat(reg_widened(), reg_v1())
    assert not c.is_equivalent and not c.can_view and c.can_upgrade
    # narrowing: nothing works
    c = schema_compat(reg_v1(), reg_widened())
    assert not c.can_view and not c.can_upgrade
    # new REQUIRED field: existing documents can't satisfy it
    c = schema_compat(reg_new_required_field(), reg_v1())
    assert not c.can_view and not c.can_upgrade
    # new OPTIONAL field: upgradeable
    c = schema_compat(reg_new_optional_field(), reg_v1())
    assert not c.can_view and c.can_upgrade
    # multiplicity widening value -> optional is an upgrade
    v = SchemaRegistry(); v.add(array_schema("list", {"number"}))
    v.root = FieldSchema(FieldKind.OPTIONAL, {"list"})
    c = schema_compat(v, reg_v1())
    assert not c.can_view and c.can_upgrade
    # multiplicity narrowing optional -> value is not
    c = schema_compat(reg_v1(), v)
    assert not c.can_view and not c.can_upgrade


def test_view_with_upgrade_flow():
    svc, doc, a, b = setup_pair()
    ta, tb = tree_of(a), tree_of(b)
    ta.set_schema(reg_v1())
    a.flush(); doc.process_all()
    # B opens with a WIDER schema: not viewable as-is, upgradeable.
    vb = tb.view_with(reg_widened())
    compat = vb.compatibility
    assert not compat.can_view and compat.can_upgrade and not compat.is_equivalent
    with pytest.raises(RuntimeError):
        _ = vb.root
    vb.upgrade_schema()
    # Locally upgraded: the view opens immediately (optimistic schema).
    assert vb.compatibility.can_view
    b.flush(); doc.process_all()
    assert ta.schema.to_json() == reg_widened().to_json()
    # A client with the OLD schema can no longer view (stored is wider now).
    va = ta.view_with(reg_v1())
    assert not va.compatibility.can_view
    with pytest.raises(RuntimeError):
        _ = va.root
    with pytest.raises(RuntimeError):
        va.upgrade_schema()


def test_view_with_equivalent_upgrade_is_noop():
    svc, doc, a, b = setup_pair()
    ta = tree_of(a)
    ta.set_schema(reg_v1())
    a.flush(); doc.process_all()
    v = ta.view_with(reg_v1())
    assert v.compatibility.is_equivalent
    v.upgrade_schema()  # no-op: ships nothing
    a.flush()
    assert not a.has_pending_changes if hasattr(a, "has_pending_changes") else True
    doc.process_all()
    assert tree_of(b).schema.to_json() == reg_v1().to_json()
