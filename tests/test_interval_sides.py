"""Sided interval endpoints and stickiness.

Mirrors the reference's sided-interval suites (sequence
intervalCollection with intervalStickinessEnabled: merge-tree
sequencePlace.ts Side/normalizePlace, sequence intervals/intervalUtils.ts
computeStickinessFromSide, sequenceInterval.ts slide-to-endpoint):
- insert adjacency for every side combination (stickiness),
- slide-on-remove direction per side, degrading to the start/end sentinels,
- "start"/"end" literal endpoints,
- convergence, summary round-trip, and reconnect resubmit with sides.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.dds.sequence_intervals import (
    SENTINEL_POS,
    IntervalStickiness,
    Side,
    compute_stickiness,
    normalize_place,
    transform_place,
)
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService

pytestmark = pytest.mark.usefixtures("string_backend")


def make_container(doc, name: str, stash: str | None = None) -> ContainerRuntime:
    c = ContainerRuntime(default_registry(), container_id=name)
    ds = c.create_datastore("root")
    ds.create_channel("sharedString", "text")
    c.connect(doc, name, stash=stash)
    return c


def string_of(c):
    return c.datastore("root").get_channel("text")


def setup_pair():
    svc = LocalService()
    doc = svc.document("d1")
    a = make_container(doc, "A")
    b = make_container(doc, "B")
    doc.process_all()
    return svc, doc, a, b


def seeded(doc, a, text="hello world"):
    string_of(a).insert_text(0, text)
    a.flush()
    doc.process_all()


def places(c, label="c1"):
    coll = string_of(c).get_interval_collection(label)
    return {
        iv.interval_id: (iv.start, iv.start_side, iv.end, iv.end_side)
        for iv in coll
    }


def covered(c, iid, label="c1"):
    """The substring the interval covers in the (fully acked) text."""
    s = string_of(c)
    iv = s.get_interval_collection(label).get(iid)
    n = len(s.text)
    lo, hi = iv.first_char(n), iv.last_char(n)
    return s.text[lo : hi + 1] if hi >= lo else ""


# ---------------------------------------------------------------- unit level

def test_normalize_and_stickiness():
    assert normalize_place(5) == (5, Side.BEFORE)
    assert normalize_place((5, Side.AFTER)) == (5, Side.AFTER)
    assert normalize_place("start") == (SENTINEL_POS, Side.AFTER)
    assert normalize_place("end") == (SENTINEL_POS, Side.BEFORE)
    # ref intervalUtils.ts: START from an After start, END from a Before end.
    assert compute_stickiness(Side.BEFORE, Side.AFTER) == IntervalStickiness.NONE
    assert compute_stickiness(Side.AFTER, Side.AFTER) == IntervalStickiness.START
    assert compute_stickiness(Side.BEFORE, Side.BEFORE) == IntervalStickiness.END
    assert compute_stickiness(Side.AFTER, Side.BEFORE) == IntervalStickiness.FULL


def test_transform_place_insert_and_remove():
    # Anchors follow their character on insert.
    assert transform_place(6, Side.BEFORE, "insert", 6, 3) == (9, Side.BEFORE)
    assert transform_place(6, Side.AFTER, "insert", 7, 3) == (6, Side.AFTER)
    # Remove: BEFORE slides forward, AFTER slides backward.
    assert transform_place(6, Side.BEFORE, "remove", 4, 4) == (4, Side.BEFORE)
    assert transform_place(6, Side.AFTER, "remove", 4, 4) == (3, Side.AFTER)
    # Backward off the front: the "start" sentinel.
    assert transform_place(2, Side.AFTER, "remove", 0, 5) == (
        SENTINEL_POS, Side.AFTER,
    )
    # Sentinels never move.
    assert transform_place(SENTINEL_POS, Side.BEFORE, "insert", 0, 9) == (
        SENTINEL_POS, Side.BEFORE,
    )


# ------------------------------------------------------- stickiness (insert)

def test_nonsticky_start_excludes_adjacent_insert():
    svc, doc, a, b = setup_pair()
    seeded(doc, a)  # "hello world"
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add((6, Side.BEFORE), (10, Side.AFTER))  # "world"
    a.flush(); doc.process_all()
    assert covered(a, iid) == "world"
    string_of(b).insert_text(6, "big ")
    b.flush(); doc.process_all()
    assert covered(a, iid) == covered(b, iid) == "world"
    assert places(a) == places(b) == {iid: (10, Side.BEFORE, 14, Side.AFTER)}


def test_sticky_start_includes_adjacent_insert():
    svc, doc, a, b = setup_pair()
    seeded(doc, a)
    ca = string_of(a).get_interval_collection("c1")
    # Anchor after char 5 (' '): first char 6, START sticky.
    iid = ca.add((5, Side.AFTER), (10, Side.AFTER))
    a.flush(); doc.process_all()
    assert covered(a, iid) == "world"
    string_of(b).insert_text(6, "big ")
    b.flush(); doc.process_all()
    assert covered(a, iid) == covered(b, iid) == "big world"
    assert places(a) == places(b) == {iid: (5, Side.AFTER, 14, Side.AFTER)}


def test_sticky_end_includes_adjacent_insert():
    svc, doc, a, b = setup_pair()
    seeded(doc, a)
    ca = string_of(a).get_interval_collection("c1")
    # End before char 10 ('d'): last char 9, END sticky at that boundary.
    iid = ca.add((6, Side.BEFORE), (10, Side.BEFORE))
    a.flush(); doc.process_all()
    assert covered(a, iid) == "worl"
    string_of(b).insert_text(10, "XY")
    b.flush(); doc.process_all()
    assert covered(a, iid) == covered(b, iid) == "worlXY"
    assert places(a) == places(b) == {iid: (6, Side.BEFORE, 12, Side.BEFORE)}


def test_nonsticky_end_excludes_adjacent_insert():
    svc, doc, a, b = setup_pair()
    seeded(doc, a)
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add((6, Side.BEFORE), (10, Side.AFTER))  # includes 'd'
    a.flush(); doc.process_all()
    string_of(b).insert_text(11, "!!")
    b.flush(); doc.process_all()
    assert covered(a, iid) == covered(b, iid) == "world"
    assert places(a) == places(b) == {iid: (6, Side.BEFORE, 10, Side.AFTER)}


# --------------------------------------------------------- slide (on remove)

def test_remove_slides_before_forward_and_after_backward():
    svc, doc, a, b = setup_pair()
    seeded(doc, a)  # "hello world"
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add((6, Side.BEFORE), (10, Side.AFTER))
    a.flush(); doc.process_all()
    # Remove "o wo": start char 6 dies -> slides forward to the survivor 'r'.
    string_of(b).remove_range(4, 8)
    b.flush(); doc.process_all()
    assert string_of(a).text == "hellrld"
    assert places(a) == places(b) == {iid: (4, Side.BEFORE, 6, Side.AFTER)}
    assert covered(a, iid) == "rld"
    # Remove "ld": end char dies -> slides backward to 'r'.
    string_of(b).remove_range(5, 7)
    b.flush(); doc.process_all()
    assert string_of(a).text == "hellr"
    assert places(a) == places(b) == {iid: (4, Side.BEFORE, 4, Side.AFTER)}
    assert covered(a, iid) == "r"


def test_remove_off_front_slides_to_start_sentinel():
    svc, doc, a, b = setup_pair()
    seeded(doc, a, "abcdef")
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add((1, Side.AFTER), (4, Side.AFTER))  # chars 2..4 "cde"
    a.flush(); doc.process_all()
    string_of(b).remove_range(0, 3)  # start anchor char 1 dies, nothing before
    b.flush(); doc.process_all()
    assert string_of(a).text == "def"
    assert places(a) == places(b) == {
        iid: (SENTINEL_POS, Side.AFTER, 1, Side.AFTER)
    }
    assert covered(a, iid) == "de"


def test_remove_off_back_slides_to_end_sentinel():
    svc, doc, a, b = setup_pair()
    seeded(doc, a, "abcdef")
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add((2, Side.BEFORE), (4, Side.BEFORE))  # chars 2..3 "cd"
    a.flush(); doc.process_all()
    string_of(b).remove_range(3, 6)  # end anchor char 4 dies, no survivor after
    b.flush(); doc.process_all()
    assert string_of(a).text == "abc"
    assert places(a) == places(b) == {
        iid: (2, Side.BEFORE, SENTINEL_POS, Side.BEFORE)
    }
    assert covered(a, iid) == "c"
    # END-sentinel end now sticks to appended text.
    string_of(a).insert_text(3, "zz")
    a.flush(); doc.process_all()
    assert covered(a, iid) == covered(b, iid) == "czz"


def test_crossed_endpoints_collapse_empty():
    svc, doc, a, b = setup_pair()
    seeded(doc, a, "abcdef")
    ca = string_of(a).get_interval_collection("c1")
    # start BEFORE 2, end AFTER 3; removing 2..5 slides start fwd to 2 (='f'
    # post-remove) and end backward to 1 -> crossed -> empty at start place.
    iid = ca.add((2, Side.BEFORE), (3, Side.AFTER))
    a.flush(); doc.process_all()
    string_of(b).remove_range(2, 5)
    b.flush(); doc.process_all()
    assert string_of(a).text == "abf"
    pa = places(a)
    assert pa == places(b)
    assert covered(a, iid) == ""


# --------------------------------------------------- "start"/"end" literals

def test_start_end_literals_pin_whole_string():
    svc, doc, a, b = setup_pair()
    seeded(doc, a, "middle")
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add("start", "end")
    a.flush(); doc.process_all()
    assert covered(a, iid) == "middle"
    string_of(b).insert_text(0, "<<")
    string_of(b).insert_text(8, ">>")
    b.flush(); doc.process_all()
    assert covered(a, iid) == covered(b, iid) == "<<middle>>"
    assert places(a) == places(b) == {
        iid: (SENTINEL_POS, Side.AFTER, SENTINEL_POS, Side.BEFORE)
    }


# ------------------------------------------------ change / summary / stash

def test_change_to_sided_endpoints():
    svc, doc, a, b = setup_pair()
    seeded(doc, a)
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add(0, 4)  # legacy
    a.flush(); doc.process_all()
    ca.change(iid, start=(5, Side.AFTER), end=(10, Side.BEFORE))
    a.flush(); doc.process_all()
    assert places(a) == places(b) == {iid: (5, Side.AFTER, 10, Side.BEFORE)}
    assert string_of(b).get_interval_collection("c1").get(iid).stickiness \
        == IntervalStickiness.FULL


def test_sided_change_requires_both_endpoints_and_validates():
    svc, doc, a, b = setup_pair()
    seeded(doc, a)
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add(2, 5)
    with pytest.raises(AssertionError):
        ca.change(iid, start=(1, Side.AFTER))  # sided needs both endpoints
    with pytest.raises(AssertionError):
        ca.change(iid, start=(99, Side.BEFORE), end=(100, Side.AFTER))
    # Valid sided change converts the interval; a later single-endpoint
    # legacy change reverts it wholesale (never half-sided).
    ca.change(iid, start=(1, Side.AFTER), end=(5, Side.BEFORE))
    a.flush(); doc.process_all()
    assert places(a) == places(b) == {iid: (1, Side.AFTER, 5, Side.BEFORE)}
    ca.change(iid, start=2)
    a.flush(); doc.process_all()
    iv = string_of(b).get_interval_collection("c1").get(iid)
    assert (iv.start_side, iv.end_side) == (None, None)
    assert (iv.start, iv.end) == (2, 5)


def test_summary_roundtrip_preserves_sides():
    svc, doc, a, b = setup_pair()
    seeded(doc, a)
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add((5, Side.AFTER), "end", {"k": 1})
    a.flush(); doc.process_all()
    summary = string_of(a).summarize()
    from fluidframework_tpu.dds.channels import SharedStringChannel

    fresh = SharedStringChannel("text")
    fresh.load(summary)
    got = {
        iv.interval_id: (iv.start, iv.start_side, iv.end, iv.end_side)
        for iv in fresh.get_interval_collection("c1")
    }
    assert got == {iid: (5, Side.AFTER, SENTINEL_POS, Side.BEFORE)}
    assert fresh.get_interval_collection("c1").get(iid).props == {"k": 1}


def test_reconnect_resubmit_matches_connected_end_sentinel_degrade():
    """The reconnect transform must degrade a forward slide off the back to
    the "end" sentinel exactly like connected replicas' finalize_op — same
    user actions, same converged interval either way."""
    svc, doc, a, b = setup_pair()
    seeded(doc, a, "abcdef")
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add((2, Side.BEFORE), (4, Side.BEFORE))
    a.flush()
    a.disconnect()
    string_of(b).remove_range(3, 6)  # end anchor char 4 dies, nothing after
    b.flush(); doc.process_all()
    a.connect(doc, "A2")
    a.flush(); doc.process_all()
    assert places(a) == places(b) == {
        iid: (2, Side.BEFORE, SENTINEL_POS, Side.BEFORE)
    }
    # The sticky sentinel end picks up appended text on both replicas.
    string_of(b).insert_text(3, "zz")
    b.flush(); doc.process_all()
    assert covered(a, iid) == covered(b, iid) == "czz"


def test_reconnect_resubmits_sided_pending_op():
    svc, doc, a, b = setup_pair()
    seeded(doc, a)  # "hello world"
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add((6, Side.BEFORE), (10, Side.AFTER))
    a.flush()
    # Not yet sequenced: A drops; B edits before A's op lands.
    a.disconnect()
    string_of(b).insert_text(0, ">> ")
    b.flush(); doc.process_all()
    a.connect(doc, "A2")
    a.flush(); doc.process_all()
    assert places(a) == places(b)
    assert covered(a, iid) == covered(b, iid) == "world"


def test_reconnect_keeps_anchor_in_own_pending_insert():
    """An endpoint anchored in the author's own pending (resubmitted-ahead)
    insert must survive reconnect, not collapse to the end sentinel."""
    svc, doc, a, b = setup_pair()
    seeded(doc, a, "abcdef")
    a.disconnect()
    string_of(a).insert_text(6, "xyz")
    ca = string_of(a).get_interval_collection("c1")
    iid = ca.add((6, Side.BEFORE), (8, Side.AFTER))  # over pending "xyz"
    a.connect(doc, "A2")
    a.flush(); doc.process_all()
    assert places(a) == places(b) == {iid: (6, Side.BEFORE, 8, Side.AFTER)}
    assert covered(a, iid) == covered(b, iid) == "xyz"


def test_fuzz_sided_intervals_converge():
    from fluidframework_tpu.testing.fuzz import run_fuzz_suite
    from test_fuzz_harness import STRING_MODEL

    run_fuzz_suite(STRING_MODEL, range(6), steps=60, n_clients=3)
