"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual 8-device CPU mesh exactly as the driver's
``dryrun_multichip`` does.

Note: this image's sitecustomize registers the axon TPU plugin and forces
``jax_platforms=axon,cpu`` *after* env-var processing, so JAX_PLATFORMS=cpu
alone is not enough — we must override the config after importing jax (but
before any backend initializes).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache: the suite is compile-dominated on small CI
# boxes (hundreds of unique engine/kernel geometries, each a multi-second
# XLA compile), and every pytest process recompiles from scratch.  Caching
# compiled executables on disk makes reruns bounded by actual test work.
# Opt out with FFTPU_TEST_COMPILE_CACHE=0; the dir is gitignored.
if os.environ.get("FFTPU_TEST_COMPILE_CACHE", "1") != "0":
    _cache_dir = os.environ.get(
        "FFTPU_TEST_COMPILE_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     ".jax_compile_cache"),
    )
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import pytest  # noqa: E402

# Modules whose every test triggers JAX kernel compilation (the expensive
# lane).  Everything else is host-plane Python and forms the <2-min smoke
# lane (`pytest -m "not device"`).
_DEVICE_MODULES = {
    "test_columnar_ingest",
    "test_dispatch_backends",
    "test_doc_batch_engine",
    "test_fleet_consumer",
    "test_kernel_channel",
    "test_long_doc",
    "test_matrix_kernel",
    "test_megastep",
    "test_mergetree_kernel",
    "test_multidevice",
    "test_native_ingest",
    "test_obliterate",
    "test_overflow_recovery",
    "test_pallas_kernels",
    "test_scribe",
    "test_segment_parallel",
    "test_shared_map",
    "test_tree_batch_engine",
    "test_tree_kernel",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__ in _DEVICE_MODULES:
            item.add_marker(pytest.mark.device)
            continue
        # The kernel leg of dual-backend tests compiles the merge-tree
        # kernel; the oracle leg stays in the fast lane.
        callspec = getattr(item, "callspec", None)
        if callspec is not None and callspec.params.get("string_backend") == "kernel":
            item.add_marker(pytest.mark.device)


@pytest.fixture(params=["oracle", "kernel"])
def string_backend(request):
    """Run a test once on the Python oracle and once with the TPU kernel
    behind the channel boundary (the north star's plugin gate,
    ref datastore-definitions/src/channel.ts:294).  Modules opt in with
    ``pytestmark = pytest.mark.usefixtures("string_backend")``."""
    if request.param == "kernel":
        from fluidframework_tpu.dds import channels
        from fluidframework_tpu.dds.kernel_backend import KernelMergeTree

        channels.set_string_backend_factory(
            lambda: KernelMergeTree(
                max_segments=1024,
                remove_slots=6,
                prop_slots=4,
                text_capacity=16384,
                max_insert_len=16,
                ob_slots=16,
            )
        )
        yield "kernel"
        channels.set_string_backend_factory(None)
    else:
        yield "oracle"
