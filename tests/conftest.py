"""Test configuration: force an 8-device virtual CPU platform.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual 8-device CPU mesh exactly as the driver's
``dryrun_multichip`` does.

Note: this image's sitecustomize registers the axon TPU plugin and forces
``jax_platforms=axon,cpu`` *after* env-var processing, so JAX_PLATFORMS=cpu
alone is not enough — we must override the config after importing jax (but
before any backend initializes).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
