"""Cross-process service-plane tests: a standalone server process, client
containers in separate OS processes, real TCP/HTTP in between.

(The full loader suite also runs over the network driver in-process via the
parametrized ``env`` fixture in test_loader.py; this module proves the
plane works across PROCESS boundaries — the reference's client/service
split.)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CLIENT_SCRIPT = r"""
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.driver.network_driver import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Container

port, http_port = int(sys.argv[1]), int(sys.argv[2])
factory = NetworkDocumentServiceFactory("127.0.0.1", port, http_port)
c = Container.load("doc", factory, default_registry(), "procB")
factory.sync_all()
s = c.runtime.datastore("root").get_channel("text")
before = s.text
s.insert_text(len(s.text), " world")
c.runtime.flush()
factory.sync_all()
print(json.dumps({"before": before, "after": s.text}), flush=True)
c.disconnect()
"""


@pytest.fixture
def server_proc():
    proc = subprocess.Popen(
        [sys.executable, "-m", "fluidframework_tpu.server.netserver", "--port", "0"],
        stdout=subprocess.PIPE,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    ready = json.loads(proc.stdout.readline())
    yield ready["port"], ready["httpPort"]
    proc.terminate()
    proc.wait(timeout=10)


def test_two_process_convergence(server_proc):
    port, http_port = server_proc
    from fluidframework_tpu.dds.channels import default_registry
    from fluidframework_tpu.driver.network_driver import NetworkDocumentServiceFactory
    from fluidframework_tpu.loader import Container

    factory = NetworkDocumentServiceFactory("127.0.0.1", port, http_port)
    d = Container.create_detached(default_registry(), container_id="procA")
    ds = d.runtime.create_datastore("root")
    ds.create_channel("sharedString", "text")
    d.attach("doc", factory, "procA")
    s = d.runtime.datastore("root").get_channel("text")
    s.insert_text(0, "hello")
    d.runtime.flush()
    factory.sync_all()

    # A second OS process loads the same document, reads, edits, exits.
    out = subprocess.run(
        [sys.executable, "-c", CLIENT_SCRIPT, str(port), str(http_port)],
        capture_output=True,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["before"] == "hello"
    assert result["after"] == "hello world"

    # Process A sees process B's edit through the broadcast.
    factory.sync_all()
    assert s.text == "hello world"


def test_cross_process_concurrent_edits(server_proc):
    """Both processes edit concurrently (neither has seen the other's op
    when it submits); the sequencer orders them and both converge."""
    port, http_port = server_proc
    from fluidframework_tpu.dds.channels import default_registry
    from fluidframework_tpu.driver.network_driver import NetworkDocumentServiceFactory
    from fluidframework_tpu.loader import Container

    factory = NetworkDocumentServiceFactory("127.0.0.1", port, http_port)
    d = Container.create_detached(default_registry(), container_id="procA")
    d.runtime.create_datastore("root").create_channel("sharedString", "text")
    d.attach("doc", factory, "procA")
    s = d.runtime.datastore("root").get_channel("text")
    s.insert_text(0, "base")
    d.runtime.flush()
    factory.sync_all()

    concurrent = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.driver.network_driver import NetworkDocumentServiceFactory
from fluidframework_tpu.loader import Container
port, http_port = int(sys.argv[1]), int(sys.argv[2])
factory = NetworkDocumentServiceFactory("127.0.0.1", port, http_port)
c = Container.load("doc", factory, default_registry(), "procB")
factory.sync_all()
s = c.runtime.datastore("root").get_channel("text")
s.insert_text(0, "B")          # submitted before pumping A's concurrent op
c.runtime.flush()
deadline = time.time() + 60
while "A" not in s.text:       # wait until A's concurrent op arrives
    factory.sync_all()
    if time.time() > deadline:
        break
    time.sleep(0.02)
print(json.dumps({"text": s.text}), flush=True)
c.disconnect()
"""
    proc = subprocess.Popen(
        [sys.executable, "-c", concurrent, str(port), str(http_port)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    # A edits concurrently (without pumping B's op first).
    s.insert_text(0, "A")
    d.runtime.flush()
    out, err = proc.communicate(timeout=120)
    assert proc.returncode == 0, err
    factory.sync_all()
    other = json.loads(out.strip().splitlines()[-1])["text"]
    assert s.text == other, f"{s.text!r} != {other!r}"
    assert sorted(s.text) == sorted("ABbase")
