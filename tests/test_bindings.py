"""View bindings (ref packages/framework/react hooks): subscribe, rerender
gate, derived bindings, unmount cleanup — over live two-client sessions."""

from __future__ import annotations

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.framework.bindings import (
    use_channel,
    use_shared_map,
    use_shared_string,
    use_tree,
)
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService


def host():
    svc = LocalService()
    doc = svc.document("d")
    rts = []
    for i in range(2):
        rt = ContainerRuntime(default_registry(), container_id=f"c{i}")
        ds = rt.create_datastore("root")
        ds.create_channel("sharedString", "text")
        ds.create_channel("sharedMap", "kv")
        rt.connect(doc, f"c{i}")
        rts.append(rt)
    doc.process_all()

    def settle():
        for rt in rts:
            rt.flush()
        doc.process_all()

    return doc, rts, settle


def test_map_binding_rerenders_only_on_relevant_change():
    doc, (a, b), settle = host()
    binding = use_shared_map(b, "root", "kv")
    renders = []
    binding.on_change(renders.append)

    a.datastore("root").get_channel("kv").set("x", 1)
    settle()
    assert renders == [{"x": 1}] and binding.value == {"x": 1}

    # Ops to a DIFFERENT channel never fire this binding.
    a.datastore("root").get_channel("text").insert_text(0, "hi")
    settle()
    assert renders == [{"x": 1}]

    # A same-channel op that does not change the selected value is gated.
    a.datastore("root").get_channel("kv").set("x", 1)
    settle()
    assert renders == [{"x": 1}]
    a.datastore("root").get_channel("kv").set("x", 2)
    settle()
    assert renders == [{"x": 1}, {"x": 2}]


def test_string_binding_local_echo_and_remote_update():
    doc, (a, b), settle = host()
    a.datastore("root").get_channel("text").insert_text(0, "local")
    bind_a = use_shared_string(a, "root", "text")
    assert bind_a.value == "local"  # optimistic read before sequencing
    renders = []
    bind_a.on_change(renders.append)
    settle()
    # Own op sequenced: the selected value matches the last snapshot (the
    # optimistic echo was already visible), so no rerender.
    assert renders == []
    b.datastore("root").get_channel("text").insert_text(0, "remote-")
    settle()
    assert renders == ["remote-local"]


def test_derived_binding_and_dispose():
    doc, (a, b), settle = host()
    kv = use_shared_map(b, "root", "kv")
    count = kv.map(len)
    hits = []
    count.on_change(hits.append)
    a.datastore("root").get_channel("kv").set("k1", 1)
    settle()
    a.datastore("root").get_channel("kv").set("k1", 99)  # same key count
    settle()
    assert hits == [1]  # derived gate: len unchanged on overwrite
    n_listeners = len(b.op_processed_listeners)
    count.dispose()
    kv.dispose()
    assert len(b.op_processed_listeners) == n_listeners - 2
    a.datastore("root").get_channel("kv").set("k2", 2)
    settle()
    assert hits == [1]  # unmounted: no further renders
    count.dispose()  # idempotent


def test_tree_binding():
    svc = LocalService()
    doc = svc.document("d")
    rt = ContainerRuntime(default_registry(), container_id="c0")
    rt.create_datastore("root").create_channel("sharedTree", "t")
    rt.connect(doc, "c0")
    doc.process_all()
    from fluidframework_tpu.dds.tree.changeset import make_insert
    from fluidframework_tpu.dds.tree.schema import leaf

    binding = use_tree(rt, "root", "t")
    renders = []
    binding.on_change(renders.append)
    rt.datastore("root").get_channel("t").submit_change(
        make_insert([], "", 0, [leaf(42)])
    )
    rt.flush()
    doc.process_all()
    assert renders and renders[-1][0]["v"] == 42
