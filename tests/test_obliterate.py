"""Obliterate semantics: directed edge cases + obliterate-heavy farms.

Reference analog: merge-tree client.obliterateFarm.spec.ts plus the directed
obliterate suites (obliterate.spec.ts, obliterateSided tests).  Every
directed test runs on BOTH backends (Python oracle and TPU kernel); the farm
runs kernel-backed clients against an oracle observer replica.
"""

import random

import pytest

from fluidframework_tpu.dds.kernel_backend import KernelMergeTree
from fluidframework_tpu.dds.shared_string import SharedString
from fluidframework_tpu.protocol.stamps import ALL_ACKED, acked
from fluidframework_tpu.server.local_service import LocalDocument

from test_mergetree_oracle import canon_annotations, draw_op, issue_op, pump


def make_backend(which: str):
    if which == "oracle":
        return None  # SharedString defaults to RefMergeTree
    return KernelMergeTree(max_insert_len=8, ob_slots=16)


def make_doc(which: str, n: int):
    doc = LocalDocument("d")
    clients = [
        SharedString(client_id=f"c{i}", backend=make_backend(which))
        for i in range(n)
    ]
    for c in clients:
        doc.connect(c.client_id, c.process)
    doc.process_all()
    return doc, clients


BACKENDS = ("oracle", "kernel")


@pytest.mark.parametrize("which", BACKENDS)
class TestDirectedObliterate:
    def test_basic_obliterate(self, which):
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "hello world")
        pump(doc, [a, b])
        a.obliterate_range(5, 11)
        pump(doc, [a, b])
        assert a.text == b.text == "hello"

    def test_concurrent_insert_into_obliterated_range_is_swallowed(self, which):
        """The defining obliterate behavior (vs set-remove): an insert
        concurrent with an obliterate covering its position is swallowed
        (ref mergeTree.ts blockInsert obliterate handling)."""
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "abcd")
        pump(doc, [a, b])
        a.obliterate_range(0, 4)
        b.insert_text(2, "X")  # concurrent: lands inside the obliterated range
        pump(doc, [a, b])
        assert a.text == b.text == ""

    def test_obliterater_own_insert_survives(self, which):
        """The obliterating client's own insert into the range survives
        (last-obliterater-gets-to-insert)."""
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "abcd")
        pump(doc, [a, b])
        a.obliterate_range(0, 4)
        a.insert_text(0, "Y")  # a's view: text already empty locally
        pump(doc, [a, b])
        assert a.text == b.text == "Y"

    def test_remote_obliterate_splices_over_local_pending_remove(self, which):
        """ADVICE round-2 high: a remote obliterate must still stamp segments
        covered only by an UNACKED LOCAL remove (RemoteObliteratePerspective,
        perspective.ts:201 — local remove stamps have not 'occurred').  If it
        skips them, replicas disagree on the remove set once the local remove
        acks, and any op with refSeq in [ob.seq, removeAck.seq) resolves
        positions differently."""
        doc, (a, b, c) = make_doc(which, 3)
        a.insert_text(0, "abcdefgh")
        pump(doc, [a, b, c])
        a.remove_range(1, 4)       # local pending remove of 'bcd' (not flushed)
        b.obliterate_range(1, 5)   # concurrent obliterate of 'bcde'
        for m in b.take_outbox():
            doc.submit(m)
        doc.process_all()          # ob sequenced; a's remove still pending
        for m in a.take_outbox():  # a's remove sequenced next
            doc.submit(m)
        # c op with refSeq = ob.seq (c has not seen a's remove): intends 'fg'
        # of its view "afgh".
        c.remove_range(1, 3)
        for m in c.take_outbox():
            doc.submit(m)
        doc.process_all()
        pump(doc, [a, b, c])
        assert a.text == b.text == c.text == "ah"

    def test_overlapping_remove_and_obliterate_converge(self, which):
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "abcdef")
        pump(doc, [a, b])
        a.remove_range(1, 4)
        b.obliterate_range(2, 6)
        pump(doc, [a, b])
        assert a.text == b.text == "a"

    def test_last_obliterater_wins_insert(self, which):
        """Two concurrent obliterates over one range; the LATER-sequenced
        obliterater's concurrent insert into the range survives (ref
        obliteratePrecedingInsertion last-obliterater-wins)."""
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "abcd")
        pump(doc, [a, b])
        a.obliterate_range(0, 4)
        for m in a.take_outbox():
            doc.submit(m)
        b.obliterate_range(0, 4)   # sequenced after a's
        b.insert_text(0, "Z")      # b: the newest obliterater inserts
        pump(doc, [a, b])
        assert a.text == b.text == "Z"

    def test_earlier_obliterater_front_insert_escapes_later_obliterate(self, which):
        """a obliterates, inserts Y at the front (protected by its own ob),
        then b's concurrent obliterate of the same chars is sequenced later.
        Y landed BEFORE b's start anchor char (tie-break front placement), so
        it is outside b's window and survives (ref nodeMap: a zero-length-at-
        refSeq segment at the walk start satisfies start >= nextPos and is
        skipped; insert-time findOverlapping likewise has idx <= start)."""
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "abcd")
        pump(doc, [a, b])
        a.obliterate_range(0, 4)
        a.insert_text(0, "Y")
        for m in a.take_outbox():
            doc.submit(m)
        b.obliterate_range(0, 4)   # sequenced last; b had not seen a's ops
        pump(doc, [a, b])
        assert a.text == b.text == "Y"

    def test_sided_obliterate_expand_after_start(self, which):
        """(c, After) start excludes c itself but swallows concurrent inserts
        landing right after it."""
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "abcd")
        pump(doc, [a, b])
        # Obliterate (0, After)..(3, After): keeps 'a', removes 'bcd'.
        a.obliterate_range_sided((0, False), (3, False))
        b.insert_text(1, "X")  # concurrent insert right after 'a': swallowed
        pump(doc, [a, b])
        assert a.text == b.text == "a"

    def test_sided_obliterate_before_end_swallows_adjacent_insert(self, which):
        """A (c, Before) end excludes char c from removal, but the endpoint
        sticks to c: a concurrent insert landing just before c is still
        inside the window and is swallowed (the sided-expansion behavior the
        plain form (c-1, After) would NOT have)."""
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "abcd")
        pump(doc, [a, b])
        # Obliterate (1, Before)..(3, Before): removes 'bc', keeps 'a','d'.
        a.obliterate_range_sided((1, True), (3, True))
        b.insert_text(3, "X")  # boundary before 'd': inside the sided window
        pump(doc, [a, b])
        assert a.text == b.text == "ad"

    def test_obliterate_then_msn_expiry_allows_reuse(self, which):
        """Obliterates below the MSN leave the window; later inserts at the
        same spot are unaffected."""
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "abcdef")
        pump(doc, [a, b])
        a.obliterate_range(1, 5)
        pump(doc, [a, b])
        # Both clients op again so MSN advances past the obliterate.
        a.insert_text(0, "x")
        pump(doc, [a, b])
        b.insert_text(0, "y")
        pump(doc, [a, b])
        a.insert_text(2, "Q")
        pump(doc, [a, b])
        assert a.text == b.text

    def test_obliterate_survives_segment_splits(self, which):
        """Anchors must follow splits: insert inside the obliterated window
        after boundary segments were split by unrelated edits."""
        doc, (a, b) = make_doc(which, 2)
        a.insert_text(0, "aabbccdd")
        pump(doc, [a, b])
        a.obliterate_range(2, 6)   # 'bbcc'
        b.remove_range(0, 1)       # concurrent edit splits position space
        b.insert_text(3, "M")      # concurrent insert inside the ob window
        pump(doc, [a, b])
        assert a.text == b.text == "add"


@pytest.mark.parametrize("seed", range(30))
def test_obliterate_farm_oracle(seed):
    """Obliterate-weighted multi-client farm on the oracle backend
    (ref client.obliterateFarm.spec.ts)."""
    rng = random.Random(7000 + seed)
    doc = LocalDocument("d")
    n = rng.randint(2, 4)
    clients = [SharedString(client_id=f"c{i}") for i in range(n)]
    for c in clients:
        doc.connect(c.client_id, c.process)
    doc.process_all()

    for _round in range(rng.randint(4, 10)):
        for c in clients:
            for _ in range(rng.randint(0, 3)):
                issue_op(c, draw_op(rng, len(c.text)))
            if rng.random() < 0.7:
                for m in c.take_outbox():
                    doc.submit(m)
        doc.process_some(rng.randint(0, doc.pending_count))

    pump(doc, clients)
    texts = {c.text for c in clients}
    assert len(texts) == 1, f"divergent texts: {texts}"
    anns = {canon_annotations(c) for c in clients}
    assert len(anns) == 1, "divergent annotations"


@pytest.mark.parametrize("seed", range(12))
def test_obliterate_differential_farm(seed):
    """Obliterate-weighted differential farm: kernel-backed clients against
    an oracle observer; texts and annotations must match exactly (the
    oracle-vs-kernel equivalence gate for obliterate)."""
    rng = random.Random(8000 + seed)
    doc = LocalDocument("d")
    n = rng.randint(2, 3)
    clients = [
        SharedString(
            client_id=f"c{i}",
            backend=KernelMergeTree(max_insert_len=8, ob_slots=16),
        )
        for i in range(n)
    ]
    oracle = SharedString(client_id="oracle")
    for c in clients:
        doc.connect(c.client_id, c.process)
    doc.connect(oracle.client_id, oracle.process)
    doc.process_all()

    for _round in range(rng.randint(4, 8)):
        for c in clients:
            for _ in range(rng.randint(0, 2)):
                issue_op(c, draw_op(rng, len(c.text)))
            if rng.random() < 0.7:
                for m in c.take_outbox():
                    doc.submit(m)
        doc.process_some(rng.randint(0, doc.pending_count))

    pump(doc, clients + [oracle])
    expected = oracle.text
    for c in clients:
        assert c.backend.check_errors() == 0, f"kernel error flags (seed {seed})"
        assert c.text == expected, f"kernel diverged from oracle (seed {seed})"
    anns = {canon_annotations(c) for c in clients}
    anns.add(canon_annotations(oracle))
    assert len(anns) == 1, "annotation divergence"


def test_remove_set_after_splice_matches_between_replicas():
    """After the splice fix, every replica holds the SAME remove-stamp set
    on overlap segments (the state-level assertion behind the regression)."""
    doc = LocalDocument("d")
    a, b = [SharedString(client_id=f"c{i}") for i in range(2)]
    for c in (a, b):
        doc.connect(c.client_id, c.process)
    doc.process_all()
    a.insert_text(0, "abcdef")
    pump(doc, [a, b])
    a.remove_range(1, 4)       # pending local remove
    b.obliterate_range(0, 6)
    for m in b.take_outbox():
        doc.submit(m)
    doc.process_all()          # remote obliterate splices over a's pending remove
    pump(doc, [a, b])          # a's remove acks
    assert a.text == b.text == ""

    def stamp_sets(client):
        return sorted(
            tuple(sorted((k, cl) for k, cl in s.removes))
            for s in client.backend.segments
            if s.removes and acked(s.ins_key)
        )

    assert stamp_sets(a) == stamp_sets(b)
    # The overlap segment carries BOTH stamps on both replicas.
    overlap = [s for s in a.backend.segments if len(s.removes) >= 2]
    assert overlap, "expected an overlap segment with both remove stamps"
