"""Loader + driver layer tests: load/attach, catch-up, snapshots, quorum,
reconnect epochs, read/write escalation, gap repair, signals.

Models the reference's container-loader tests + local-server integration
suites (SURVEY §4.4): full Loader→Runtime→DDS stacks against the in-process
service through the driver interfaces.
"""

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.server import LocalService

pytestmark = pytest.mark.usefixtures("string_backend")



@pytest.fixture(params=["local", "network"])
def env(request):
    """Every loader test runs twice: once against the in-process service,
    once over REAL TCP/HTTP sockets through the network driver (the
    round-3 service plane; ref nexus/index.ts:127 + alfred routes)."""
    if request.param == "local":
        svc = LocalService()
        yield svc, LocalDocumentServiceFactory(svc)
    else:
        from fluidframework_tpu.testing.network_env import NetworkTestService

        net = NetworkTestService()
        yield net, net.factory
        net.close()


def load(factory, name, **kw):
    c = Container.load("doc", factory, default_registry(), name, **kw)
    return c


def string_of(c):
    return c.runtime.datastore("root").get_channel("text")


def boot_doc(factory):
    """First client creates the structure via detached create + attach."""
    d = Container.create_detached(default_registry(), container_id="creator")
    ds = d.runtime.create_datastore("root")
    ds.create_channel("sharedString", "text")
    ds.create_channel("sharedMap", "meta")
    return d


class TestLoadAttach:
    def test_detached_attach_then_load_converges(self, env):
        svc, factory = env
        d = boot_doc(factory)
        string_of(d).insert_text(0, "hello")  # detached edit parks
        d.runtime.flush()
        d.attach("doc", factory, "creator")
        svc.process_all()
        assert string_of(d).text == "hello"

        # Second client loads purely from the service (snapshot has the
        # structure; content arrives as trailing ops).
        c2 = load(factory, "reader")
        svc.process_all()
        assert string_of(c2).text == "hello"

        # Live collaboration after load.
        string_of(c2).insert_text(5, "!")
        c2.runtime.flush()
        svc.process_all()
        assert string_of(d).text == "hello!"

    def test_load_from_snapshot_with_trailing_ops(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        string_of(d).insert_text(0, "base")
        d.runtime.flush()
        svc.process_all()

        # Snapshot at current seq, then more ops after it.
        seq = d.summarize_to_storage()
        assert seq == d.runtime.ref_seq
        string_of(d).insert_text(4, " + trailing")
        d.runtime.flush()
        svc.process_all()

        c2 = load(factory, "late")
        svc.process_all()
        assert string_of(c2).text == "base + trailing"
        # The loader started from the snapshot: its delta manager only
        # processed ops above the snapshot seq.
        assert c2.delta_manager.last_processed_seq >= seq

    def test_read_mode_then_escalate(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        string_of(d).insert_text(0, "abc")
        d.runtime.flush()
        svc.process_all()

        r = load(factory, "viewer", mode="read")
        svc.process_all()
        assert string_of(r).text == "abc"
        assert not r.joined  # read connections never join the quorum
        assert "viewer" not in svc.document("doc").sequencer.clients()

        # Local edit while read-only parks; escalation replays it.
        string_of(r).insert_text(3, "!")
        r.runtime.flush()
        r.escalate_to_write()
        svc.process_all()
        assert string_of(d).text == "abc!"
        assert string_of(r).text == "abc!"


class TestQuorum:
    def test_propose_accepts_on_msn(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        c2 = load(factory, "other")
        svc.process_all()

        d.propose("code", {"package": "fluidframework-tpu@0.1"})
        svc.process_all()
        # Proposal sequenced but MSN hasn't passed it: still pending until
        # every client references a later seq.
        accepted_now = d.protocol.quorum.has("code")
        string_of(c2).insert_text(0, "x")
        c2.runtime.flush()
        string_of(d).insert_text(0, "y")
        d.runtime.flush()
        svc.process_all()
        assert d.protocol.quorum.get("code") == {"package": "fluidframework-tpu@0.1"}
        assert c2.protocol.quorum.get("code") == {"package": "fluidframework-tpu@0.1"}
        # Accept seq identical on both replicas.
        assert (
            d.protocol.quorum.values["code"][1] == c2.protocol.quorum.values["code"][1]
        )
        assert not accepted_now or d.protocol.quorum.values["code"][1] <= d.protocol.min_seq

    def test_quorum_membership_tracks_joins_leaves(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        c2 = load(factory, "other")
        svc.process_all()
        assert set(d.protocol.quorum.members) == {"creator", "other"}
        c2.disconnect()
        svc.process_all()
        assert set(d.protocol.quorum.members) == {"creator"}


class TestReconnect:
    def test_reconnect_new_epoch_replays_pending(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        c2 = load(factory, "other")
        svc.process_all()

        c2.disconnect()
        string_of(c2).insert_text(0, "offline")
        c2.runtime.flush()  # parks as pending
        c2.reconnect()
        svc.process_all()
        assert string_of(d).text == "offline"
        assert c2.delta_manager.connection_manager.client_id == "other~r1"
        assert c2.joined

    def test_nack_then_reconnect(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        c2 = load(factory, "other")
        svc.process_all()

        # Force a nack: corrupt the client's view by submitting with a future
        # refSeq via the raw connection.
        from fluidframework_tpu.protocol.messages import UnsequencedMessage

        conn = c2.delta_manager.connection_manager.connection
        conn.submit(
            UnsequencedMessage(
                client_id=conn.client_id, client_seq=999, ref_seq=10**9
            )
        )
        svc.process_all()  # a networked nack arrives asynchronously
        assert not c2.connected
        assert c2.delta_manager.connection_manager.next_backoff_s > 0
        c2.reconnect()
        svc.process_all()
        string_of(c2).insert_text(0, "recovered")
        c2.runtime.flush()
        svc.process_all()
        assert string_of(d).text == "recovered"


class TestDeltaManager:
    def test_gap_repair_from_delta_storage(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        string_of(d).insert_text(0, "abcdef")
        d.runtime.flush()
        svc.process_all()

        c2 = load(factory, "other")
        svc.process_all()
        # Simulate a dropped broadcast: deliver an op out of order directly.
        doc = svc.document("doc")
        string_of(d).insert_text(6, "XYZ")
        d.runtime.flush()
        # Tip the queue: skip delivery for c2 by delivering only to d, then
        # inject the NEXT op to c2 first (out-of-order arrival).
        string_of(d).insert_text(9, "!")
        d.runtime.flush()
        # Over a real wire the submits are asynchronous: a sync marker on
        # d's socket is ordered BEHIND them, so after it the server has
        # ticketed both (local connections have no sync; already ticketed).
        conn_d = d.delta_manager.connection_manager.connection
        if hasattr(conn_d, "sync"):
            conn_d.sync()
        msgs = list(doc.sequencer.log[-2:])
        # Deliver newest first to c2's delta manager: forces gap fetch.
        c2.delta_manager._on_stream(msgs[1])
        assert string_of(c2).text == "abcdefXYZ!"
        svc.process_all()  # regular delivery still consistent (dedup)
        assert string_of(c2).text == "abcdefXYZ!"
        assert string_of(d).text == "abcdefXYZ!"

    def test_pause_resume(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        c2 = load(factory, "other")
        svc.process_all()
        c2.delta_manager.pause()
        string_of(d).insert_text(0, "zz")
        d.runtime.flush()
        svc.process_all()
        assert string_of(c2).text == ""
        c2.delta_manager.resume()
        assert string_of(c2).text == "zz"


class TestSignals:
    def test_signal_broadcast_unsequenced(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        c2 = load(factory, "other")
        svc.process_all()
        got = []
        c2.on_signal(lambda s: got.append((s.client_id, s.contents)))
        d.submit_signal({"cursor": [1, 2]})
        svc.process_all()  # networked signals arrive asynchronously
        assert got == [("creator", {"cursor": [1, 2]})]
        # Signals leave no trace in the op log.
        before = len(svc.document("doc").sequencer.log)
        d.submit_signal({"cursor": [3, 4]})
        svc.process_all()
        assert len(svc.document("doc").sequencer.log) == before


class TestProposalRejection:
    def test_disconnect_before_sequencing_rejects_proposal(self, env):
        """A proposal in flight when the connection drops is surfaced as
        rejected (the reference rejects the propose promise on disconnect)
        instead of vanishing silently."""
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        svc.process_all()

        d.propose("code", {"package": "pkg@1"})
        d.disconnect()  # before the proposal is delivered back
        assert d.runtime.rejected_proposals == [
            {"type": "propose", "contents": {"key": "code", "value": {"package": "pkg@1"}}}
        ]
        svc.process_all()
        # A sequenced proposal is NOT rejected by a later disconnect.
        d.connect()
        svc.process_all()
        d.runtime.rejected_proposals.clear()
        d.propose("code", {"package": "pkg@2"})
        svc.process_all()
        d.disconnect()
        assert d.runtime.rejected_proposals == []


class TestStash:
    def test_stash_through_loader(self, env):
        svc, factory = env
        d = boot_doc(factory)
        d.attach("doc", factory, "creator")
        c2 = load(factory, "other")
        svc.process_all()

        c2.disconnect()
        string_of(c2).insert_text(0, "stashed-edit ")
        stash = c2.get_pending_local_state()

        c3 = load(factory, "resumed", stash=stash)
        svc.process_all()
        assert string_of(c3).text == "stashed-edit "
        assert string_of(d).text == "stashed-edit "
