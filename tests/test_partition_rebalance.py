"""Partition rebalance: document ownership moves between workers with
checkpoint handoff (VERDICT r3 missing #7; ref lambdas-driver
partitionManager.ts).

The kill tests pin the core contract: a partition's documents resume on a
surviving worker from the last periodic checkpoint with NO op loss and NO
duplication in the sequenced log, even when the dead worker had processed
(and produced side effects for) records beyond that checkpoint.
"""

from __future__ import annotations



from fluidframework_tpu.protocol.messages import (
    MessageType,
    SequencedMessage,
    UnsequencedMessage,
)
from fluidframework_tpu.server.partition_manager import PartitionManager


def op(client: str, cseq: int, ref: int = 1, body: str = "x") -> UnsequencedMessage:
    return UnsequencedMessage(
        client_id=client, client_seq=cseq, ref_seq=ref,
        type=MessageType.OP, contents={"type": 0, "pos1": 0, "seg": body},
    )


DOCS = [f"doc{i}" for i in range(8)]


def feed(pm: PartitionManager, start: int, count: int) -> None:
    for doc in DOCS:
        for i in range(start + 1, start + count + 1):  # clientSeq is 1-based
            pm.submit_op(doc, op("w", i, ref=1))


def seqs_of(pm: PartitionManager, doc: str) -> list[int]:
    """Per-doc sequence numbers as recorded in the deltas LOG (the durable
    truth a rebalance must never corrupt)."""
    p = pm.deltas.partition_for(doc)
    return [
        rec.payload.seq
        for rec in pm.deltas.partition(p).read(0)
        if rec.doc_id == doc and rec.payload.type == MessageType.OP
    ]


def assert_no_loss_no_dup(pm: PartitionManager, expected_ops: int) -> None:
    for doc in DOCS:
        seqs = seqs_of(pm, doc)
        assert len(seqs) == expected_ops, (doc, len(seqs))
        assert seqs == sorted(seqs)
        assert len(set(seqs)) == len(seqs), f"duplicated seqs in {doc}"


def test_round_robin_assignment_and_join_rebalance():
    pm = PartitionManager(n_partitions=4)
    pm.add_worker("a")
    assert pm.assignments() == {"a": [0, 1, 2, 3]}
    pm.add_worker("b")
    assert pm.assignments() == {"a": [0, 2], "b": [1, 3]}
    pm.add_worker("c")
    assert pm.assignments() == {"a": [0, 3], "b": [1], "c": [2]}


def test_join_mid_stream_moves_partitions_without_disruption():
    pm = PartitionManager(n_partitions=4)
    pm.add_worker("a")
    for doc in DOCS:
        pm.join(doc, "w")
    feed(pm, 0, 5)
    pm.pump()
    pm.add_worker("b")  # live move with checkpoint handoff
    feed(pm, 5, 5)
    pm.pump()
    assert_no_loss_no_dup(pm, 10)


def test_graceful_remove_resumes_seamlessly():
    pm = PartitionManager(n_partitions=4)
    pm.add_worker("a")
    pm.add_worker("b")
    for doc in DOCS:
        pm.join(doc, "w")
    feed(pm, 0, 4)
    pm.pump()
    pm.remove_worker("b")  # checkpoints its partitions on the way out
    assert pm.assignments() == {"a": [0, 1, 2, 3]}
    feed(pm, 4, 4)
    pm.pump()
    assert_no_loss_no_dup(pm, 8)


def test_kill_mid_stream_no_loss_no_dup():
    """THE rebalance contract: kill a worker whose partitions have both
    unprocessed input AND side effects beyond the last checkpoint; the
    successors replay from the checkpoint without losing or duplicating a
    single sequenced op."""
    pm = PartitionManager(n_partitions=4)
    pm.add_worker("a")
    pm.add_worker("b")
    for doc in DOCS:
        pm.join(doc, "w")
    feed(pm, 0, 4)
    pm.pump()  # processes + periodic checkpoint

    # New input lands; the victim processes SOME of it directly (side
    # effects hit the deltas log) but the manager never checkpoints again.
    feed(pm, 4, 3)
    for lams in pm.workers["b"].values():
        lams.pump()  # beyond-checkpoint progress that will be replayed

    pm.kill_worker("b")
    assert pm.assignments() == {"a": [0, 1, 2, 3]}
    feed(pm, 7, 3)
    pm.pump()
    assert_no_loss_no_dup(pm, 10)
    # And the op stores converge with the log (deterministic rebuild).
    for doc in DOCS:
        assert [m.seq for m in pm.ops_of(doc) if m.type == MessageType.OP] == seqs_of(pm, doc)


def test_kill_preserves_summary_state_and_never_reacks():
    """Summaries processed before the kill survive the move, and replaying
    the summarize op on the new owner does not re-emit its ack."""
    pm = PartitionManager(n_partitions=2)
    pm.add_worker("a")
    pm.add_worker("b")
    doc = "doc0"
    pm.join(doc, "w")
    pm.submit_op(doc, op("w", 1))
    pm.pump()
    h = pm.upload_summary({"type": "blob", "content": {"s": 1}})
    pm.rawdeltas.produce(doc, ("service", (MessageType.SUMMARIZE, {"handle": h, "refSeq": 1})))
    victim = pm.owner_of(pm.deltas.partition_for(doc))
    # The victim processes the summarize (snapshot + ack into rawdeltas)
    # and even sequences the ack — all beyond the last checkpoint.
    for _ in range(4):
        for lams in pm.workers[victim].values():
            lams.pump()
    pm.kill_worker(victim)
    pm.pump()
    assert len(pm.snapshots_of(doc)) == 1
    responses = [
        rec.payload.type
        for rec in pm.deltas.partition(pm.deltas.partition_for(doc)).read(0)
        if rec.payload.type in (MessageType.SUMMARY_ACK, MessageType.SUMMARY_NACK)
    ]
    # Exactly ONE response total: no re-sequenced ack, and no spurious
    # nack from the replayed SUMMARIZE finding its consumed handle gone.
    assert responses == [MessageType.SUMMARY_ACK], responses


def test_subscribers_reattach_across_kill():
    pm = PartitionManager(n_partitions=2)
    pm.add_worker("a")
    pm.add_worker("b")
    doc = "doc0"
    pm.join(doc, "w")
    seen: list[int] = []
    last = [0]

    def on_msg(msg: SequencedMessage) -> None:
        # Client-side at-least-once dedup by seq (the DeltaManager rule).
        if msg.seq > last[0]:
            last[0] = msg.seq
            if msg.type == MessageType.OP:
                seen.append(msg.seq)

    pm.subscribe(doc, on_msg)
    pm.submit_op(doc, op("w", 1))
    pm.submit_op(doc, op("w", 2))
    pm.pump()
    victim = pm.owner_of(pm.deltas.partition_for(doc))
    pm.submit_op(doc, op("w", 3))
    for lams in pm.workers[victim].values():
        lams.pump()  # broadcast beyond checkpoint, then die
    pm.kill_worker(victim)
    pm.submit_op(doc, op("w", 4))
    pm.pump()
    assert seen == sorted(set(seen))
    assert len(seen) == 4, f"subscriber missed ops: {seen}"


def test_no_workers_queues_until_one_joins():
    pm = PartitionManager(n_partitions=2)
    pm.join("doc0", "w")
    pm.submit_op("doc0", op("w", 1))
    assert pm.pump() == 0  # nothing owns the partitions yet
    pm.add_worker("a")
    pm.pump()
    assert [m.seq for m in pm.ops_of("doc0") if m.type == MessageType.OP] == [2]


def test_consumer_group_pins_and_topic_placement():
    """Mesh-alignment primitives: ``Topic.place`` overrides the hash route
    for pinned docs only; ``ConsumerGroup.pin`` gives a partition to one
    member while it lives and falls back to round-robin when it dies."""
    from fluidframework_tpu.server.ordered_log import ConsumerGroup, Topic

    topic = Topic("deltas", n_partitions=4)
    hash_route = topic.partition_for("docA")
    topic.place("docA", (hash_route + 1) % 4)
    assert topic.partition_for("docA") == (hash_route + 1) % 4
    assert topic.partition_for("docB") == sum(b"docB") % 4  # unpinned
    try:
        topic.place("docA", 7)
    except ValueError:
        pass
    else:  # pragma: no cover
        raise AssertionError("out-of-range placement accepted")

    group = ConsumerGroup(topic, "g")
    group.join("a")
    group.join("b")
    group.pin(1, "b")
    group.pin(3, "b")
    assert group.assignments("b") == [1, 3]
    assert group.assignments("a") == [0, 2]
    # The pinned member dies: its pins fall back to round-robin, nothing
    # is stranded.
    group.leave("b")
    assert group.assignments("a") == [0, 1, 2, 3]
