"""Observability plane tests (ISSUE 7): histogram percentile correctness
against numpy quantiles, flight-recorder ring wraparound, Chrome-trace JSON
schema validity, /metrics text-format parse round-trip, recompile watchdog,
the telemetry satellites (PerformanceEvent start timestamp,
SampledTelemetryHelper.flush_all), the fftpu-trace summarizer, and an e2e
smoke asserting a fleet run produces ingest -> upload -> dispatch ->
readback spans with consistent nesting plus a scrapeable metrics surface.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from fluidframework_tpu.models.doc_batch_engine import DocBatchEngine
from fluidframework_tpu.observability import (
    FlightRecorder,
    MetricsPlane,
    MetricsServer,
    RecompileWatchdog,
    install,
    parse_prometheus,
    phase_totals,
    render_prometheus,
    uninstall,
)
from fluidframework_tpu.observability.flight_recorder import phase_shares
from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage
from fluidframework_tpu.server.fleet_consumer import FleetConsumer
from fluidframework_tpu.server.netserver import NetworkServer
from fluidframework_tpu.tools import trace_viewer
from fluidframework_tpu.utils.telemetry import (
    Histogram,
    Logger,
    PerformanceEvent,
    SampledTelemetryHelper,
)


@pytest.fixture(autouse=True)
def _no_global_recorder():
    """Every test starts and ends with no global recorder installed."""
    uninstall()
    yield
    uninstall()


# ---------------------------------------------------------------------------
# Histogram
# ---------------------------------------------------------------------------


class TestHistogram:
    def test_empty_and_single_sample(self):
        h = Histogram()
        assert h.percentile(0.5) is None
        assert h.snapshot() == {"count": 0}
        h.record(0.0042)
        # Single sample: clamping to [min, max] makes the answer exact.
        for q in (0.0, 0.5, 0.99, 1.0):
            assert h.percentile(q) == pytest.approx(0.0042)
        snap = h.snapshot()
        assert snap["count"] == 1 and snap["p99"] == pytest.approx(0.0042)

    @pytest.mark.parametrize("dist", ["uniform", "lognormal"])
    def test_percentiles_vs_numpy(self, dist):
        rng = np.random.default_rng(7)
        if dist == "uniform":
            samples = rng.uniform(1e-5, 1e-1, size=5000)
        else:
            samples = np.exp(rng.normal(-7.0, 1.5, size=5000))
        h = Histogram()
        for v in samples:
            h.record(float(v))
        for q in (0.5, 0.9, 0.99):
            got = h.percentile(q)
            want = float(np.quantile(samples, q))
            # Log-bucketed: within one bucket (factor `growth`) of exact.
            assert want / h.growth <= got <= want * h.growth, (q, got, want)
        assert h.count == len(samples)
        assert h.min == pytest.approx(samples.min())
        assert h.max == pytest.approx(samples.max())
        assert h.sum == pytest.approx(samples.sum(), rel=1e-9)

    def test_merge_equals_single(self):
        rng = np.random.default_rng(3)
        samples = rng.uniform(1e-6, 1e-2, size=2000)
        whole, a, b = Histogram(), Histogram(), Histogram()
        for v in samples:
            whole.record(float(v))
        for v in samples[:777]:
            a.record(float(v))
        for v in samples[777:]:
            b.record(float(v))
        a.merge(b)
        assert a.count == whole.count and a.sum == pytest.approx(whole.sum)
        for q in (0.5, 0.9, 0.99):
            assert a.percentile(q) == whole.percentile(q)

    def test_merge_empty_and_layout_mismatch(self):
        a, b = Histogram(), Histogram()
        a.record(1.0)
        a.merge(b)  # merging an empty histogram is a no-op
        assert a.count == 1 and a.percentile(0.5) == pytest.approx(1.0)
        with pytest.raises(ValueError, match="layouts"):
            a.merge(Histogram(growth=2.0))
        with pytest.raises(ValueError):
            a.percentile(1.5)


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_wraparound(self):
        rec = FlightRecorder(capacity=8)
        for i in range(20):
            rec.instant(f"e{i}")
        assert len(rec) == 8
        assert rec.dropped == 12
        names = [e.name for e in rec.events()]
        assert names == [f"e{i}" for i in range(12, 20)]  # oldest first
        ts = [e.ts_ns for e in rec.events()]
        assert ts == sorted(ts)

    def test_span_nesting_and_instants(self):
        rec = install(FlightRecorder())
        from fluidframework_tpu.observability import instant, span

        with span("outer", k=1):
            with span("inner"):
                pass
            instant("mark", x=2)
        evs = rec.events()
        by_name = {e.name: e for e in evs}
        assert by_name["outer"].ph == "X" and by_name["outer"].args == {"k": 1}
        # inner is contained in outer (complete events record at exit, so
        # inner lands first, but its window nests inside outer's).
        outer, inner = by_name["outer"], by_name["inner"]
        assert outer.ts_ns <= inner.ts_ns
        assert inner.ts_ns + inner.dur_ns <= outer.ts_ns + outer.dur_ns
        assert by_name["mark"].ph == "i"

    def test_noop_without_recorder(self):
        from fluidframework_tpu.observability import instant, span

        with span("free"):  # no recorder installed: must not raise
            instant("free2")

    def test_chrome_trace_schema(self, tmp_path):
        rec = FlightRecorder()
        with rec.span("phase_a", doc="d0"):
            pass
        rec.instant("recompile", program="p")
        path = tmp_path / "trace.json"
        n = rec.export_chrome_trace(str(path))
        assert n == 2
        doc = json.loads(path.read_text())
        assert isinstance(doc["traceEvents"], list)
        assert doc["displayTimeUnit"] == "ms"
        for ev in doc["traceEvents"]:
            assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)
            assert ev["ph"] in ("X", "i")
            if ev["ph"] == "X":
                assert "dur" in ev and ev["dur"] >= 0
            else:
                assert ev["s"] == "t"
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert spans[0]["args"] == {"doc": "d0"}

    def test_phase_totals_and_shares(self):
        rec = FlightRecorder()
        with rec.span("a"):
            pass
        with rec.span("a"):
            pass
        with rec.span("b"):
            pass
        totals = phase_totals(rec.events())
        assert set(totals) == {"a", "b"} and totals["a"] >= 0
        shares = phase_shares(rec.events())
        assert sum(shares.values()) == pytest.approx(1.0, abs=0.01)


class TestRecompileWatchdog:
    def test_counts_cache_growth(self):
        import jax

        fn = jax.jit(lambda x: x + 1)
        if not hasattr(fn, "_cache_size"):
            pytest.skip("jax has no _cache_size probe")
        rec = install(FlightRecorder())
        wd = RecompileWatchdog()
        wd.register("probe", fn)
        wd.register("probe", fn)  # idempotent
        wd.register("not_jitted", lambda x: x)  # ignored
        assert wd.poll() == 0
        fn(np.zeros((2,), np.float32))
        first = wd.poll()
        assert first >= 1 and wd.recompiles == first
        # A NEW shape after the program specialized = de-specialization:
        # counted AND emits the instant event.
        fn(np.zeros((3,), np.float32))
        assert wd.poll() >= 1
        assert wd.per_program["probe"] == wd.recompiles >= 2
        assert any(e.name == "recompile" for e in rec.events())


# ---------------------------------------------------------------------------
# Metrics plane
# ---------------------------------------------------------------------------


class TestMetricsPlane:
    def test_render_parse_round_trip(self):
        h = Histogram()
        for v in (0.001, 0.002, 0.004, 0.1):
            h.record(v)
        tree = {
            "engine": {
                "rows": 42,
                "ok": True,
                "shard_queue_depth": [3, 0, 7],
                "label": "not-a-metric",
            },
            "latency": {"op_latency": h},
        }
        text = render_prometheus(tree)
        parsed = parse_prometheus(text)
        assert parsed[("fftpu_engine_rows", ())] == 42.0
        assert parsed[("fftpu_engine_ok", ())] == 1.0
        assert parsed[
            ("fftpu_engine_shard_queue_depth", (("idx", "2"),))
        ] == 7.0
        assert parsed[("fftpu_latency_op_latency_count", ())] == 4.0
        p50 = parsed[("fftpu_latency_op_latency", (("quantile", "0.5"),))]
        assert 0.001 <= p50 <= 0.01
        # Non-numeric leaves are /status-only.
        assert not any("label" in name for name, _ in parsed)

    def test_netserver_http_front_routes(self):
        from fluidframework_tpu.server.netserver import ServicePlane

        plane = ServicePlane().start()
        try:
            with plane.nexus.lock:
                plane.service.document("d0")
            base = f"http://127.0.0.1:{plane.http.port}"
            text = urllib.request.urlopen(f"{base}/metrics").read().decode()
            parsed = parse_prometheus(text)
            assert parsed[("fftpu_n_docs", ())] == 1.0
            assert parsed[("fftpu_docs_d0_log_depth", ())] == 0.0
            status = json.loads(
                urllib.request.urlopen(f"{base}/status").read()
            )
            assert status["docs"]["d0"]["pending"] == 0
            assert status["uptime_s"] >= 0
        finally:
            plane.stop()

    def test_scribe_state_and_log_depth_scrape(self, tmp_path):
        """Scribe pool state + ordered-log depth flow through the plane:
        fold spans land in the trace, health renders as gauges."""
        from fluidframework_tpu.server.ordered_log import DurableTopic
        from fluidframework_tpu.server.scribe import ScribeConfig, ScribeLambda

        rec = install(FlightRecorder())
        topic = DurableTopic(
            "deltas", 1, str(tmp_path / "log"),
            encode=lambda m: m.to_json(),
            decode=SequencedMessage.from_json,
        )
        try:
            topic.produce("d0", SequencedMessage(
                seq=0, min_seq=0, ref_seq=0, client_id="w0", client_seq=0,
                type=MessageType.JOIN,
                contents={"clientId": "w0", "short": 0},
            ))
            for s in range(1, 5):
                topic.produce("d0", SequencedMessage(
                    seq=s, min_seq=0, ref_seq=s - 1, client_id="w0",
                    client_seq=s, type=MessageType.OP,
                    contents={"type": 0, "pos1": 0, "seg": "ab"},
                ))
            scribe = ScribeLambda(
                topic, str(tmp_path / "scribe"),
                config=ScribeConfig(max_ops=2),
            )
            try:
                scribe.pump()
                names = {e.name for e in rec.events()}
                assert {"scribe.fold", "scribe.summarize",
                        "scribe.ack"} <= names
                plane = MetricsPlane()
                plane.register("scribe", scribe.health)
                parsed = parse_prometheus(plane.metrics_text())
                assert parsed[("fftpu_scribe_summaries_written", ())] >= 1
                assert ("fftpu_scribe_log_lag", ()) in parsed
                assert (
                    "fftpu_scribe_log_depth", (("idx", "0"),)
                ) in parsed
            finally:
                scribe.close()
        finally:
            topic.close()

    def test_server_scrape(self):
        plane = MetricsPlane()
        plane.register("src", lambda: {"value": 5, "note": "text"})
        plane.register("bad", lambda: 1 / 0)
        srv = MetricsServer(plane, port=0).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            text = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert parse_prometheus(text)[("fftpu_src_value", ())] == 5.0
            status = json.loads(
                urllib.request.urlopen(f"{base}/status").read()
            )
            assert status["src"] == {"value": 5, "note": "text"}
            assert "scrape_error" in status["bad"]
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(f"{base}/nope")
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Telemetry satellites
# ---------------------------------------------------------------------------


class TestTelemetrySatellites:
    def test_performance_event_start_timestamp(self):
        import time as _time

        log = Logger()
        before = _time.time()
        with PerformanceEvent(log, "load", docId="d"):
            pass
        (e,) = log.matching(category="performance")
        # Backward-compatible schema: old fields intact, startTime added.
        assert e["eventName"] == "load_end" and e["duration"] >= 0
        assert before <= e["startTime"] <= _time.time()

    def test_performance_event_cancel_carries_start(self):
        log = Logger()
        with pytest.raises(RuntimeError):
            with PerformanceEvent(log, "load"):
                raise RuntimeError("boom")
        (e,) = log.matching(category="error")
        assert e["startTime"] > 0

    def test_flush_all_drains_residual_buckets(self):
        log = Logger()
        h = SampledTelemetryHelper(log, "applyOp", sample_every=10)
        for _ in range(7):
            h.record(0.001, bucket="insert")
        for _ in range(3):
            h.record(0.002, bucket="remove")
        assert not log.matching(eventName="applyOp")  # below sample_every
        assert h.flush_all() == 2
        events = log.matching(eventName="applyOp")
        assert {e["bucket"] for e in events} == {"insert", "remove"}
        assert sum(e["count"] for e in events) == 10
        assert h.flush_all() == 0  # idempotent once drained

    def test_engine_flush_telemetry_via_status_snapshot(self):
        from fluidframework_tpu.server.fleet_main import status_snapshot

        log = Logger()
        eng = DocBatchEngine(
            1, max_segments=64, text_capacity=512, max_insert_len=8,
            ops_per_step=4, use_mesh=False, recovery="off", telemetry=log,
        )
        _feed_engine(eng, n_docs=1, rounds=3)
        assert not log.matching(eventName="engine_step")  # below sample_every
        snap = status_snapshot(eng, ["d0"])
        (e,) = log.matching(eventName="engine_step")
        assert e["bucket"] == "step" and e["count"] == 3
        assert snap["health"]["latency_samples"] >= 0


# ---------------------------------------------------------------------------
# Engine integration: latency histograms + spans + metrics surface
# ---------------------------------------------------------------------------


def _feed_engine(eng, n_docs: int, rounds: int, seq0: int = 0) -> int:
    for d in range(n_docs):
        if seq0 == 0:
            eng.ingest(d, SequencedMessage(
                seq=0, min_seq=0, ref_seq=0, client_id="w0", client_seq=0,
                type=MessageType.JOIN,
                contents={"clientId": "w0", "short": 0},
            ))
    seq = seq0
    for _r in range(rounds):
        idxs, msgs = [], []
        seq += 1
        for d in range(n_docs):
            idxs.append(d)
            msgs.append(SequencedMessage(
                seq=seq, min_seq=0, ref_seq=seq - 1, client_id="w0",
                client_seq=seq, type=MessageType.OP,
                contents={"type": 0, "pos1": 0, "seg": "ab"},
            ))
        eng.ingest_batch(idxs, msgs)
        eng.step()
    return seq


class TestEngineObservability:
    def test_latency_histograms_in_health(self):
        eng = DocBatchEngine(
            2, max_segments=64, text_capacity=512, max_insert_len=8,
            ops_per_step=4, use_mesh=False, recovery="off",
            latency_sample_every=1,
        )
        _feed_engine(eng, n_docs=2, rounds=4)
        h = eng.health()
        assert h["latency_samples"] == 8
        assert h["latency_p99_ms"] >= h["latency_p50_ms"] >= 0
        hists = eng.latency_histograms()
        assert hists["op_latency"].count == 8
        assert eng.doc_latency(0).count == 4
        assert eng.doc_latency(1).count == 4

    def test_engine_spans_and_metrics_text(self):
        rec = install(FlightRecorder())
        eng = DocBatchEngine(
            2, max_segments=64, text_capacity=512, max_insert_len=8,
            ops_per_step=4, use_mesh=False, recovery="grow",
            latency_sample_every=1,
        )
        _feed_engine(eng, n_docs=2, rounds=2)
        names = {e.name for e in rec.events()}
        assert {"ingest", "upload", "dispatch"} <= names
        plane = MetricsPlane()
        plane.register("engine", eng.health)
        plane.register("latency", eng.latency_histograms)
        parsed = parse_prometheus(plane.metrics_text())
        assert parsed[("fftpu_engine_latency_samples", ())] > 0
        assert ("fftpu_engine_recompiles", ()) in parsed
        assert parsed[
            ("fftpu_latency_op_latency", (("quantile", "0.99"),))
        ] > 0


# ---------------------------------------------------------------------------
# fftpu-trace CLI
# ---------------------------------------------------------------------------


class TestTraceViewer:
    def test_summarize_trace_file(self, tmp_path, capsys):
        rec = FlightRecorder()
        with rec.span("dispatch", k=4):
            with rec.span("upload", shards=1):
                pass
        rec.instant("recompile", program="fleet_megastep", cache_size=2)
        rec.instant("migrate_doc", doc="d0", src=0, dst=1)
        path = str(tmp_path / "t.json")
        rec.export_chrome_trace(path)
        assert trace_viewer.main([path, "--top", "5"]) == 0
        out = capsys.readouterr().out
        assert "phase shares" in out
        assert "dispatch" in out and "upload" in out
        assert "recompile events: 1" in out
        assert "fleet_megastep" in out
        assert "migrate_doc" in out

    def test_bad_file(self, tmp_path, capsys):
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        assert trace_viewer.main([str(bad)]) == 1


# ---------------------------------------------------------------------------
# E2E smoke: a fleet run traces end to end and scrapes
# ---------------------------------------------------------------------------


def _assert_consistent_nesting(events) -> None:
    """Per thread, any two spans are either disjoint or properly nested —
    the invariant that makes the Perfetto rendering a tree."""
    by_tid: dict[int, list] = {}
    for e in events:
        if e.ph == "X":
            by_tid.setdefault(e.tid, []).append(e)
    for spans in by_tid.values():
        spans.sort(key=lambda e: (e.ts_ns, -e.dur_ns))
        for i, a in enumerate(spans):
            for b in spans[i + 1:]:
                a0, a1 = a.ts_ns, a.ts_ns + a.dur_ns
                b0, b1 = b.ts_ns, b.ts_ns + b.dur_ns
                assert b0 >= a1 or b1 <= a1, (
                    f"partial overlap: {a.name} and {b.name}"
                )


class TestFleetE2E:
    def test_fleet_run_traces_and_scrapes(self, tmp_path):
        from fluidframework_tpu.dds.shared_string import SharedString

        rec = install(FlightRecorder())
        srv = NetworkServer().start()
        try:
            rows = 0
            with srv.lock:
                doc = srv.service.document("d0")
                w = SharedString(client_id="w0")
                doc.connect(w.client_id, w.process)
                doc.process_all()
                for i in range(12):
                    w.insert_text(0, "ab")
                    for m in w.take_outbox():
                        doc.submit(m)
                        rows += 1
                doc.process_all()
            eng = DocBatchEngine(
                1, max_segments=128, text_capacity=1024, max_insert_len=8,
                ops_per_step=8, use_mesh=False, recovery="grow",
                latency_sample_every=1,
            )
            fc = FleetConsumer("127.0.0.1", srv.port, eng, ["d0"])
            try:
                fc.run_for(rows)
                assert eng.text(0) == w.text
            finally:
                fc.close()
        finally:
            srv.stop()

        events = rec.events()
        names = {e.name for e in events}
        # The full pipeline left its trace: wire decode -> staging upload
        # -> megastep dispatch -> error-latch readback.
        assert {"ingest", "upload", "dispatch", "readback"} <= names, names
        _assert_consistent_nesting(events)
        # Sampled e2e latency resolved through the same run.
        assert eng.op_latency.count > 0
        assert eng.health()["latency_p99_ms"] > 0
        # The trace is Perfetto-loadable JSON.
        path = str(tmp_path / "fleet.json")
        n = rec.export_chrome_trace(path)
        assert n == len(events)
        doc = json.loads(open(path).read())
        assert len(doc["traceEvents"]) == n
        # And the run scrapes: engine health + latency through one plane.
        plane = MetricsPlane()
        plane.register("fleet", eng.health)
        plane.register("latency", eng.latency_histograms)
        parsed = parse_prometheus(plane.metrics_text())
        assert parsed[("fftpu_fleet_latency_samples", ())] > 0
        assert ("fftpu_latency_op_latency", (("quantile", "0.5"),)) in parsed
