"""Snapshot virtualization, caching, retry, and prefetch (odsp-driver +
driver-utils analogs).

Mirrors the reference's odsp snapshot-virtualization behavior
(odspDocumentStorageService: skeleton + on-demand content-addressed
chunks, warm-cache boots fetch only what changed), driver-web-cache
persistence, and driver-utils runWithRetry/PrefetchDocumentStorageService.
"""

from __future__ import annotations

import json

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.driver import (
    DriverError,
    LocalDocumentServiceFactory,
    PrefetchStorageService,
    SnapshotCache,
    ThrottlingError,
    VirtualizedDocumentServiceFactory,
    VirtualizedStorageService,
    run_with_retry,
)
from fluidframework_tpu.driver.virtual_storage import (
    VBLOB_KEY,
    hydrate_summary,
    shred_summary,
)
from fluidframework_tpu.loader import Container
from fluidframework_tpu.server import LocalService


def big_summary() -> dict:
    return {
        "runtime": {
            "seq": 7,
            "datastores": {
                f"ds{i}": {"channels": {"text": {"segments": [f"x{i}" * 150]}}}
                for i in range(4)
            },
        },
        "protocol": {"quorum": {"small": 1}},
    }


class CountingStore:
    """In-memory StorageService counting blob reads (the wire)."""

    def __init__(self) -> None:
        self.blobs: dict[str, str] = {}
        self.snapshot: tuple[int, dict] | None = None
        self.reads = 0

    def upload_blob_content(self, content: str) -> str:
        import hashlib

        bid = hashlib.sha256(content.encode()).hexdigest()[:32]
        self.blobs[bid] = content
        return bid

    def read_blob_content(self, blob_id: str) -> str:
        self.reads += 1
        return self.blobs[blob_id]

    def get_latest_snapshot(self):
        return self.snapshot

    def write_snapshot(self, seq: int, summary: dict) -> None:
        self.snapshot = (seq, summary)

    def upload_summary(self, summary_tree: dict) -> str:
        return "h"


# --------------------------------------------------------------- shredding

def test_shred_hydrate_roundtrip():
    store: dict[str, str] = {}

    def up(content: str) -> str:
        bid = f"b{len(store)}"
        store[bid] = content
        return bid

    original = big_summary()
    skeleton = shred_summary(original, up, threshold=128)
    assert store, "nothing was shredded"
    assert json.dumps(skeleton).find("x0" * 150) == -1, "big content left inline"
    assert hydrate_summary(skeleton, store.__getitem__) == original


def test_shred_escapes_marker_shaped_dicts():
    # Marker-shaped user data with a NON-string payload escapes cleanly
    # (string payloads are reserved: they ARE markers and pass through).
    original = {"runtime": {VBLOB_KEY: 42}, "protocol": {}}
    skeleton = shred_summary(original, lambda c: "never", threshold=10_000)
    assert hydrate_summary(skeleton, lambda b: "") == original


def test_reshredding_unhydrated_skeleton_preserves_content():
    """write_snapshot over a lazily-read snapshot (or a dict() copy of one,
    which bypasses hydration) must keep chunk markers resolvable rather
    than corrupting them into literal content."""
    store = CountingStore()
    v = VirtualizedStorageService(store, threshold=128)
    v.write_snapshot(1, big_summary())
    _, lazy = v.get_latest_snapshot()
    raw_copy = dict.copy(lazy)  # unhydrated: still contains markers
    v.write_snapshot(2, raw_copy)
    reader = VirtualizedStorageService(store, cache=SnapshotCache(), threshold=128)
    _, snap = reader.get_latest_snapshot()
    assert snap["runtime"] == big_summary()["runtime"]
    # And the LazySnapshot direct path hydrates before shredding.
    _, lazy2 = v.get_latest_snapshot()
    v.write_snapshot(3, lazy2)
    _, snap3 = reader.get_latest_snapshot()
    assert snap3["runtime"] == big_summary()["runtime"]


def test_unchanged_subtrees_keep_their_chunk_ids():
    store = CountingStore()
    v = VirtualizedStorageService(store, threshold=128)
    s1 = big_summary()
    v.write_snapshot(1, s1)
    ids1 = set(store.blobs)
    s2 = big_summary()
    s2["runtime"]["datastores"]["ds0"]["channels"]["text"]["segments"] = ["y" * 300]
    v.write_snapshot(2, s2)
    ids2 = set(store.blobs)
    # Content addressing: only the changed chunk (and its ancestors) are new.
    assert ids1 <= ids2
    assert 0 < len(ids2 - ids1) < len(ids1)


# ------------------------------------------------------- lazy + cache reads

def test_lazy_snapshot_partial_hydration():
    store = CountingStore()
    writer = VirtualizedStorageService(store, threshold=128)
    writer.write_snapshot(3, big_summary())
    # A cold reader (separate cache) hydrates per top-level key.
    reader = VirtualizedStorageService(store, cache=SnapshotCache(), threshold=128)
    seq, snap = reader.get_latest_snapshot()
    assert seq == 3
    _ = snap["protocol"]
    protocol_reads = store.reads
    _ = snap["runtime"]
    assert store.reads > protocol_reads, "runtime subtree fetched on access"
    assert snap["runtime"] == big_summary()["runtime"]
    # Memoized: second access fetches nothing.
    before = store.reads
    _ = snap["runtime"]
    assert store.reads == before


def test_writer_cache_makes_own_reads_free():
    store = CountingStore()
    v = VirtualizedStorageService(store, threshold=128)
    v.write_snapshot(1, big_summary())
    seq, snap = v.get_latest_snapshot()
    assert snap["runtime"] == big_summary()["runtime"]
    assert store.reads == 0, "writer re-fetched chunks it just uploaded"


def test_persistent_cache_survives_restart(tmp_path):
    store = CountingStore()
    v1 = VirtualizedStorageService(
        store, cache=SnapshotCache(str(tmp_path)), threshold=128
    )
    v1.write_snapshot(1, big_summary())
    # "Restart": a fresh service instance over the same cache directory.
    v2 = VirtualizedStorageService(
        store, cache=SnapshotCache(str(tmp_path)), threshold=128
    )
    _, snap = v2.get_latest_snapshot()
    assert snap["runtime"] == big_summary()["runtime"]
    assert store.reads == 0
    assert v2.stats["cache_hits"] > 0


def test_warm_cache_never_suppresses_uploads_after_server_restart():
    """The cache is a READ cache only: a writer with a warm cache against a
    restarted (empty) server must still upload every chunk, or cold readers
    get dangling markers."""
    store = CountingStore()
    cache = SnapshotCache()
    v1 = VirtualizedStorageService(store, cache=cache, threshold=128)
    v1.write_snapshot(1, big_summary())
    store.blobs.clear()  # server restart: blob store gone, cache warm
    v2 = VirtualizedStorageService(store, cache=cache, threshold=128)
    v2.write_snapshot(2, big_summary())
    # A cold-cache reader can hydrate everything from the server alone.
    cold = VirtualizedStorageService(store, cache=SnapshotCache(), threshold=128)
    _, snap = cold.get_latest_snapshot()
    assert snap["runtime"] == big_summary()["runtime"]


def test_shred_escape_of_escape_marker_roundtrips():
    from fluidframework_tpu.driver.virtual_storage import VBLOB_ESCAPE

    original = {"runtime": {VBLOB_ESCAPE: "user"}, "p": {VBLOB_KEY: [1, 2]}}
    skeleton = shred_summary(original, lambda c: "never", threshold=10_000)
    assert hydrate_summary(skeleton, lambda b: "") == original


def test_prefetch_warms_everything():
    store = CountingStore()
    writer = VirtualizedStorageService(store, threshold=128)
    writer.write_snapshot(1, big_summary())
    reader = PrefetchStorageService(
        VirtualizedStorageService(store, cache=SnapshotCache(), threshold=128)
    )
    _, snap = reader.get_latest_snapshot()
    after_prefetch = store.reads
    assert after_prefetch > 0
    assert snap["runtime"] == big_summary()["runtime"]
    assert store.reads == after_prefetch, "hydration hit the wire after prefetch"


# ------------------------------------------------------------ run_with_retry

def test_run_with_retry_backoff_and_success():
    attempts = []
    delays = []

    def fn():
        attempts.append(1)
        if len(attempts) < 3:
            raise DriverError("transient", can_retry=True)
        return "ok"

    out = run_with_retry(fn, base_delay=0.5, sleep=delays.append)
    assert out == "ok"
    assert len(attempts) == 3
    assert delays == [0.5, 1.0]  # exponential


def test_run_with_retry_nonretryable_and_exhaustion():
    with pytest.raises(DriverError):
        run_with_retry(
            lambda: (_ for _ in ()).throw(DriverError("fatal", can_retry=False)),
            sleep=lambda d: None,
        )
    calls = []

    def always_fail():
        calls.append(1)
        raise DriverError("flaky", can_retry=True)

    with pytest.raises(DriverError):
        run_with_retry(always_fail, max_attempts=4, sleep=lambda d: None)
    assert len(calls) == 4


def test_run_with_retry_honors_throttle_retry_after():
    delays = []
    state = {"n": 0}

    def fn():
        state["n"] += 1
        if state["n"] == 1:
            raise ThrottlingError("429", retry_after=1.5)
        return "done"

    assert run_with_retry(fn, base_delay=0.01, sleep=delays.append) == "done"
    assert delays == [1.5]


# ----------------------------------------------- full container boot drive

def test_container_boot_through_virtualized_storage():
    svc = LocalService()
    inner = LocalDocumentServiceFactory(svc)
    factory = VirtualizedDocumentServiceFactory(inner, threshold=128)

    d = Container.create_detached(default_registry(), container_id="creator")
    ds = d.runtime.create_datastore("root")
    ds.create_channel("sharedString", "text")
    d.attach("doc", factory, "creator")
    s = d.runtime.datastore("root").get_channel("text")
    s.insert_text(0, "virtualized boot " * 40)
    d.runtime.flush()
    svc.process_all()
    seq = d.summarize_to_storage()
    assert seq > 0

    c2 = Container.load("doc", factory, default_registry(), "late")
    svc.process_all()
    t2 = c2.runtime.datastore("root").get_channel("text")
    assert t2.text == s.text
    # The skeleton actually stored is shredded (has chunk markers).
    raw = svc.document("doc").latest_snapshot()
    assert VBLOB_KEY in json.dumps(raw[1])
