"""Device rebase kernel: differential fuzz + byte-identity + fallbacks.

Three layers of oracle discipline, mirroring how the kernel is wired in:

* flat-leg differential fuzz — ``rebase_flat_pair_kernel`` columns vs the
  object-level ``rebase_marks`` walk, on canonical move-free mark lists;
* manager byte-identity — EditManager(device_rebase=True) vs the pooled
  fold vs the object oracle on the shared fuzz streams (summaries, fold
  stages, every trunk commit, the applied forest);
* fallback accounting — ineligible work (moves, deep paths, collisions)
  must be COUNTED into ``rebase_fallbacks``/``device_rebase_fraction``,
  never silently absorbed.
"""

from __future__ import annotations

import json
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from test_mark_pool import _engine_msgs, _fuzz_edits, _run_manager

from fluidframework_tpu.dds.tree.changeset import (
    Commit,
    Insert,
    Modify,
    NodeChange,
    Remove,
    Skip,
    apply_commit,
    clone_commit,
    commit_to_json,
    rebase_marks,
)
from fluidframework_tpu.dds.tree.editmanager import EditManager
from fluidframework_tpu.dds.tree.forest import Forest
from fluidframework_tpu.dds.tree.mark_pool import (
    F_CANONICAL,
    MarkPool,
    pool_commit_from_json,
    pool_marks,
)
from fluidframework_tpu.dds.tree.schema import leaf
from fluidframework_tpu.observability import flight_recorder as fr
from fluidframework_tpu.ops import tree_kernel as tk

M = tk.REBASE_MAX_MARKS


# ---------------------------------------------------------------------------
# Flat-leg differential fuzz: kernel columns vs the object-level walk
# ---------------------------------------------------------------------------


def _rand_marks(rng, n):
    """Canonical-biased random mark list over an n-node context."""
    marks, pos = [], 0
    last = None
    while pos < n:
        r = rng.random()
        if r < 0.25 and last != "S" and pos < n - 1:
            k = rng.randint(1, n - pos - 1)
            marks.append(Skip(k))
            pos += k
            last = "S"
        elif r < 0.5 and last != "R":
            k = rng.randint(1, n - pos)
            marks.append(Remove(k))
            pos += k
            last = "R"
        elif r < 0.75 and last != "I":
            marks.append(Insert([
                leaf(rng.randint(0, 99))
                for _ in range(rng.randint(1, 3))
            ]))
            last = "I"
        else:
            marks.append(Modify(NodeChange(value=(rng.randint(0, 9),))))
            pos += 1
            last = "M"
    if rng.random() < 0.4 and last != "I":
        marks.append(Insert([leaf(7)]))
    return marks


def _leg_sweep(seeds):
    """Both kernel legs vs rebase_marks on every canonical seed pair.

    bad legs are allowed (that is the fallback contract) but a clean leg
    must match the oracle's pooled columns exactly, and the identity bit
    must equal true columnar equality."""
    pool = MarkPool()
    pair = jax.jit(tk.rebase_flat_pair_kernel)
    total = bad_n = 0
    for seed in seeds:
        rng = random.Random(seed ^ 0x9E3779B9)
        a = _rand_marks(rng, rng.randint(0, 7))
        b = _rand_marks(rng, rng.randint(0, 7))
        try:
            pa = pool_marks(pool, a)
            ak, ac, _ = pa.columns_padded(M)
            pb = pool_marks(pool, b)
            bk, bc, _ = pb.columns_padded(M)
        except ValueError:
            continue  # wider than the kernel: the encoder gates these out
        if not (pa.flags & F_CANONICAL and pb.flags & F_CANONICAL):
            continue
        total += 1
        legA, legB = pair(jnp.asarray(ak), jnp.asarray(ac),
                          jnp.asarray(bk), jnp.asarray(bc))
        for tag, leg, src, over, aft, pin in (
            ("A", legA, a, b, True, pa),
            ("B", legB, b, a, False, pb),
        ):
            if bool(leg.bad):
                bad_n += 1
                continue
            want = rebase_marks(list(src), list(over), aft)
            try:
                wp = pool_marks(pool, want)
                wk, wc, _ = wp.columns_padded(M)
            except ValueError:
                continue
            gk = np.asarray(leg.kind)
            gc = np.asarray(leg.cnt)
            gn = int(leg.n)
            assert gn == wp.n and (gk == wk).all() and (gc == wc).all(), (
                f"leg {tag} seed={seed}: kernel columns diverge from "
                f"rebase_marks\n  src={src}\n  over={over}\n"
                f"  got k={gk[:gn]} c={gc[:gn]}\n  want={want}"
            )
            ik, ic, _ = pin.columns_padded(M)
            ident_want = (gn == pin.n) and (gk == ik).all() \
                and (gc == ic).all()
            assert bool(leg.ident) == ident_want, (
                f"leg {tag} seed={seed}: identity bit wrong "
                f"(kernel={bool(leg.ident)}, columnar={ident_want})"
            )
    assert total > seeds.stop // 4 if isinstance(seeds, range) else total
    return total, bad_n


def test_flat_leg_differential_smoke():
    total, bad_n = _leg_sweep(range(300))
    # The generator is Modify-heavy: some collision fallbacks must appear
    # (a zero here means the bad flag went dead, i.e. silent fallbacks).
    assert bad_n > 0


@pytest.mark.slow
def test_flat_leg_differential_deep():
    _leg_sweep(range(4000))


# ---------------------------------------------------------------------------
# Manager-level byte-identity: device == pooled == object oracle
# ---------------------------------------------------------------------------


def _run_device(edits):
    """The _run_manager fold, but through EditManager(device_rebase=True);
    also returns the rebaser's stats."""
    em = EditManager(mark_pool=MarkPool(), device_rebase=True)
    forest = Forest()
    trunk_json = []
    pool = em.pool
    for w, ref, seq, min_seq, commit in edits:
        wire = commit_to_json(clone_commit(commit))
        change = pool_commit_from_json(pool, wire)
        ret = em.add_sequenced(
            client_id=f"w{w}", revision=(w, seq), change=change,
            ref_seq=ref, seq=seq,
        )
        trunk_json.append(json.dumps(commit_to_json(clone_commit(ret))))
        apply_commit(forest.root, ret)
        em.advance_min_seq(min_seq)
    stages = {
        cid: [
            [[tseq, commit_to_json(cm)] for tseq, cm in st]
            for st in br.stages
        ]
        for cid, br in em.peers.items()
    }
    return (
        json.dumps(em.summarize(), sort_keys=True),
        json.dumps(stages, sort_keys=True),
        trunk_json,
        json.dumps(forest.to_json(), sort_keys=True),
        em.rebaser.stats(),
    )


def _assert_identity(edits, expect_full_device=False):
    sd, std, td, fd, stats = _run_device(edits)
    s1, st1, t1, f1 = _run_manager(edits, mark_pool=True)
    assert td == t1, "trunk commits diverge from the pooled fold"
    assert std == st1, "fold stages diverge from the pooled fold"
    assert sd == s1, "summary diverges from the pooled fold"
    assert fd == f1, "applied forest diverges from the pooled fold"
    steps = stats["device_rebase_steps"] + stats["rebase_fallbacks"]
    if steps:
        assert stats["device_rebase_fraction"] == round(
            stats["device_rebase_steps"] / steps, 4
        ), "fallbacks not accounted into the fraction gauge"
    if expect_full_device:
        assert stats["rebase_fallbacks"] == 0
        assert stats["device_rebase_fraction"] == 1.0
    return stats


@pytest.mark.parametrize("seed", [3, 4])
def test_manager_identity_mixed(seed):
    """Mixed streams (moves, optional, undo, constraints): the ineligible
    share falls back — counted — and bytes still match both oracles."""
    stats = _assert_identity(_fuzz_edits(seed, rounds=6, writers=3))
    assert stats["rebase_fallbacks"] + stats["rebase_encode_rejects"] > 0
    assert 0.0 < stats["device_rebase_fraction"] < 1.0


def test_manager_identity_clean_full_device():
    """Insert/remove/set-only streams are fully eligible: no fallbacks,
    and the device fold ALSO byte-matches the object oracle."""
    edits = _fuzz_edits(1, rounds=5, writers=3, with_moves=False,
                        with_optional=False, with_undo=False,
                        with_constraints=False)
    _assert_identity(edits, expect_full_device=True)
    s1, st1, t1, f1 = _run_manager(edits, mark_pool=True)
    s0, _st0, t0, f0 = _run_manager(edits, mark_pool=False)
    assert (s1, t1, f1) == (s0, t0, f0)


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(5, 13)))
def test_manager_identity_sweep(seed):
    _assert_identity(_fuzz_edits(seed, rounds=9, writers=4))


@pytest.mark.slow
@pytest.mark.parametrize("seed", list(range(6)))
def test_manager_identity_clean_sweep(seed):
    edits = _fuzz_edits(seed, rounds=8, writers=4, with_moves=False,
                        with_optional=False, with_undo=False,
                        with_constraints=False)
    _assert_identity(edits, expect_full_device=True)


# ---------------------------------------------------------------------------
# Engine integration: gauges and flight-recorder spans
# ---------------------------------------------------------------------------


def test_engine_device_rebase_identity_and_gauges():
    from fluidframework_tpu.models.tree_batch_engine import TreeBatchEngine

    msgs = _engine_msgs(3)

    def run(device_rebase):
        eng = TreeBatchEngine(2, capacity=4096, ops_per_step=16,
                              pool_capacity=32768, mark_pool=True,
                              device_rebase=device_rebase)
        for m in msgs:
            eng.ingest(0, m)
            eng.ingest(1, m)
        sums = [json.dumps(eng.hosts[d].em.summarize(), sort_keys=True)
                for d in range(2)]
        eng.step()
        trees = [json.dumps(eng.tree_json(d), sort_keys=True)
                 for d in range(2)]
        return eng, sums, trees

    e1, s1, t1 = run(True)
    e0, s0, t0 = run(False)
    assert s1 == s0 and t1 == t0
    h = e1.health()
    assert h["device_rebase_fraction"] == 1.0
    assert h["rebase_fallbacks"] == 0
    assert h["rebase_windows"] > 0
    assert "device_rebase_fraction" not in e0.health()


def test_rebase_kernel_spans_recorded():
    rec = fr.install(fr.FlightRecorder(capacity=4096))
    try:
        edits = _fuzz_edits(2, rounds=3, writers=2, with_moves=False,
                            with_optional=False, with_undo=False,
                            with_constraints=False)
        _run_device(edits)
        names = {ev.name for ev in rec.events()}
    finally:
        fr.uninstall()
    assert {"rebase_kernel_encode", "rebase_kernel_dispatch",
            "rebase_kernel_decode"} <= names
