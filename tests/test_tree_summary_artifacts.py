"""The reference's committed SharedTree summaries load (VERDICT r4 next
#6): every `summary-load-snapshots/singleTree-*-1.json` — BOTH compression
strategies across all seven recorded versions — decodes into this repo's
forest/schema model with identical content, and the loaded state seeds a
collaborating channel.  The reference's own regression suite loads these
same files to prove cross-version compat ("summaries written by past
versions still load with the current code", README.md).
"""

from __future__ import annotations

import os

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.dds.tree.changeset import make_insert, make_set_value
from fluidframework_tpu.dds.tree.reference_summary import (
    decode_field_batch,
    load_reference_tree_summary,
    summary_snapshot_files,
)
from fluidframework_tpu.dds.tree.schema import FieldKind, leaf
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService

ARTIFACTS = summary_snapshot_files()
pytestmark = pytest.mark.skipif(
    not ARTIFACTS, reason="reference checkout not present"
)

# The document all snapshots encode (summaryLoad.integration.ts): one
# "test schema.parent" with label "foo" and child->nodes holding two
# children with count 1 and 2.
EXPECTED = {
    "t": "test schema.parent",
    "f": {
        "child": [{
            "t": "test schema.nodes",
            "f": {"": [
                {"t": "test schema.child", "f": {"count": [{"t": "number", "v": 1}]}},
                {"t": "test schema.child", "f": {"count": [{"t": "number", "v": 2}]}},
            ]},
        }],
        "label": [{"t": "string", "v": "foo"}],
    },
}


def _canon(node_json: dict) -> dict:
    out = dict(node_json)
    if "f" in out:
        out["f"] = {
            k: [_canon(c) for c in v] for k, v in sorted(out["f"].items())
        }
    return out


@pytest.mark.parametrize(
    "path", ARTIFACTS, ids=[os.path.basename(p) for p in ARTIFACTS]
)
def test_summary_loads_with_expected_content(path):
    """Every committed summary (Compressed and Uncompressed, v2_0 through
    2.93.0) decodes to the exact document the reference wrote."""
    d = load_reference_tree_summary(path)
    assert len(d["root_field"]) == 1
    assert _canon(d["root_field"][0].to_json()) == _canon(EXPECTED)
    assert d["edit_manager"]["trunk"] == []  # summarized at rest
    assert d["detached"]["data"] == []


def test_all_versions_and_strategies_agree():
    """All 14 artifacts decode to one identical forest — cross-version and
    cross-strategy equality, the reference regression suite's invariant."""
    contents = {
        os.path.basename(p): _canon(
            load_reference_tree_summary(p)["root_field"][0].to_json()
        )
        for p in ARTIFACTS
    }
    assert len(ARTIFACTS) >= 14
    first = next(iter(contents.values()))
    for name, c in contents.items():
        assert c == first, name


def test_schema_decodes_to_registry_model():
    """The stored-schema blob maps onto this repo's SchemaRegistry: node
    kinds, field kinds, allowed types, root field."""
    d = load_reference_tree_summary(ARTIFACTS[0])
    reg = d["schema"]
    assert reg.root.kind == FieldKind.VALUE
    assert reg.root.allowed_types == {"test schema.parent"}
    parent = reg.nodes["test schema.parent"]
    assert parent.fields["label"].kind == FieldKind.VALUE
    assert parent.fields["label"].allowed_types == {"string"}
    nodes = reg.nodes["test schema.nodes"]
    assert nodes.fields[""].kind == FieldKind.SEQUENCE
    assert nodes.fields[""].allowed_types == {"test schema.child"}
    # The decoded forest VALIDATES under the decoded schema.
    errors = reg.check_node(d["root_field"][0])
    assert errors == [], errors


def test_loaded_forest_seeds_a_collaborating_channel():
    """Artifact content planted as a channel's initial state keeps
    collaborating: two replicas edit it concurrently and converge."""
    d = load_reference_tree_summary(ARTIFACTS[0])
    svc = LocalService()
    doc = svc.document("doc")
    rts = []
    for i in range(2):
        rt = ContainerRuntime(default_registry(), container_id=f"c{i}")
        rt.create_datastore("root").create_channel("sharedTree", "t")
        rt.connect(doc, f"c{i}")
        rts.append(rt)
    doc.process_all()
    tree = lambda rt: rt.datastore("root").get_channel("t")
    a, b = tree(rts[0]), tree(rts[1])
    a.submit_change(make_insert([], "", 0, [n.clone() for n in d["root_field"]]))
    for rt in rts:
        rt.flush()
    doc.process_all()
    assert _canon(b.forest.root_field[0].to_json()) == _canon(EXPECTED)
    # Concurrent edits on the artifact content.
    a.submit_change(make_set_value(
        [("", 0), ("child", 0), ("", 0), ("count", 0)], 41
    ))
    b.submit_change(make_insert(
        [("", 0), ("child", 0)], "", 2, [leaf(99)]
    ))
    for rt in rts:
        rt.flush()
    doc.process_all()
    assert a.forest.equal(b.forest)
    inner = a.forest.root_field[0].fields["child"][0].fields[""]
    assert inner[0].fields["count"][0].value == 41
    assert inner[2].value == 99


def test_field_batch_decoder_rejects_trailing_data():
    with pytest.raises(AssertionError):
        decode_field_batch(
            '{"keys":["rootFieldKey"],"fields":{"version":1,"identifiers":[],'
            '"shapes":[{"c":{"type":"x","value":true}}],'
            '"data":[[0,5,"junk"]]}}'
        )


def test_uncompressed_summaries_reencode_full_file_byte_identical():
    """The WRITE path: every Uncompressed committed summary regenerates
    from this repo's decoded model (forest nodes + schema registry + index
    stamps) to the EXACT file the reference wrote — ITree layout,
    FieldBatch encoding, SchemaString (v1 flat and v2 kind-wrapped),
    metadata stamps, tab indentation, byte for byte."""
    from fluidframework_tpu.dds.tree.reference_summary import (
        encode_reference_tree_summary,
    )

    files = summary_snapshot_files("Uncompressed")
    assert len(files) == 7
    for path in files:
        loaded = load_reference_tree_summary(path)
        regenerated = encode_reference_tree_summary(loaded)
        assert regenerated == open(path, encoding="utf-8").read(), (
            os.path.basename(path)
        )


def test_field_batch_encode_decode_roundtrip_arbitrary_docs():
    """encode_field_batch/decode_field_batch round-trip arbitrary forests
    (not just the committed document)."""
    import random

    from fluidframework_tpu.dds.tree.forest import Node
    from fluidframework_tpu.dds.tree.reference_summary import (
        decode_field_batch,
        encode_field_batch,
    )
    from fluidframework_tpu.dds.tree.schema import leaf

    rng = random.Random(7)

    def rand_node(depth):
        if depth == 0 or rng.random() < 0.5:
            return leaf(rng.choice([rng.randrange(100), "s" * rng.randint(1, 4),
                                    True, None]))
        return Node(
            type=f"T{rng.randrange(3)}",
            value=rng.randrange(10) if rng.random() < 0.4 else None,
            fields={k: [rand_node(depth - 1) for _ in range(rng.randint(1, 2))]
                    for k in rng.sample(["a", "b"], rng.randint(1, 2))},
        )

    for _ in range(10):
        field = [rand_node(3) for _ in range(rng.randint(0, 4))]
        blob = encode_field_batch(field, fields_version=2, top_version=2)
        back = decode_field_batch(blob)["rootFieldKey"]
        assert [n.to_json() for n in back] == [n.to_json() for n in field]


def test_encoder_latent_asymmetries_guarded():
    """Null leaves keep their explicit wire value; multi-key forests
    (detached subtrees) thread through the write path; schemas outside
    the registry's lossless subset refuse to regenerate."""
    import json as _json

    from fluidframework_tpu.dds.tree.reference_summary import (
        encode_field_batch,
        encode_reference_tree_summary,
    )

    # Null leaf: reference encodes [type, true, null, []].
    blob = (
        '{"keys":["rootFieldKey"],"fields":{"version":2,"identifiers":[],'
        '"shapes":[{"c":{"extraFields":1}},{"a":0}],'
        '"data":[[1,["com.fluidframework.leaf.null",true,null,[]]]]},'
        '"version":2}'
    )
    nodes = decode_field_batch(blob)["rootFieldKey"]
    assert nodes[0].type == "null" and nodes[0].value is None
    assert encode_field_batch(nodes, 2, 2) == blob

    # Multi-key forest round-trips with key order preserved.
    blob2 = _json.loads(blob)
    blob2["keys"] = ["rootFieldKey", "detached-0"]
    blob2["fields"]["data"].append(
        [1, ["com.fluidframework.leaf.number", True, 7, []]]
    )
    raw2 = _json.dumps(blob2, separators=(",", ":"))
    fields = decode_field_batch(raw2)
    assert fields["detached-0"][0].value == 7
    assert encode_field_batch(
        fields["rootFieldKey"], 2, 2,
        other_fields={"detached-0": fields["detached-0"]},
        key_order=["rootFieldKey", "detached-0"],
    ) == raw2

    # Non-lossless schema (map node) refuses to regenerate.
    loaded = load_reference_tree_summary(ARTIFACTS[0])
    loaded["format"]["schema_lossless"] = False
    with pytest.raises(ValueError):
        encode_reference_tree_summary(loaded)
