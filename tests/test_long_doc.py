"""Segment-axis sharding tests on the 8-device virtual mesh: sharded
position resolution / range marks must match the single-device kernel
bit-for-bit (the PartialSequenceLengths-replacement contract)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from fluidframework_tpu.ops import mergetree_kernel as mk
from fluidframework_tpu.parallel.long_doc import make_sharded_ops, shard_doc_state
from fluidframework_tpu.protocol.stamps import ALL_ACKED, NO_REMOVE


def build_doc(n_segs=64, seg_len=5, removed_every=7, capacity=256):
    """A single-doc state with n_segs acked segments, some removed."""
    s = mk.init_state(max_segments=capacity, remove_slots=2, prop_slots=2,
                      text_capacity=capacity * seg_len)
    seg_start = np.zeros(capacity, np.int32)
    seg_lens = np.zeros(capacity, np.int32)
    ins_key = np.zeros(capacity, np.int32)
    ins_client = np.full(capacity, -1, np.int32)
    rem0 = np.full(capacity, NO_REMOVE, np.int32)
    for i in range(n_segs):
        seg_start[i] = i * seg_len
        seg_lens[i] = seg_len
        ins_key[i] = i + 1
        ins_client[i] = 0
        if removed_every and i % removed_every == 0:
            rem0[i] = n_segs + i + 1  # acked remove
    return s._replace(
        nseg=jnp.asarray(n_segs, jnp.int32),
        seg_start=jnp.asarray(seg_start),
        seg_len=jnp.asarray(seg_lens),
        ins_key=jnp.asarray(ins_key),
        ins_client=jnp.asarray(ins_client),
        rem_keys=(jnp.asarray(rem0),) + s.rem_keys[1:],
    )


def reference_resolution(state, positions, ref_seq, client):
    """Single-device oracle: same math, no sharding."""
    vis = np.asarray(mk._visible(state, ref_seq, client))
    lens = np.where(vis, np.asarray(state.seg_len), 0)
    prefix = np.cumsum(lens) - lens
    out_idx, out_off = [], []
    for p in positions:
        inside = (p >= prefix) & (p < prefix + lens)
        idx = int(np.argmax(inside)) if inside.any() else 0
        out_idx.append(idx if inside.any() else 0)
        out_off.append(p - prefix[idx] if inside.any() else 0)
    return np.array(out_idx), np.array(out_off)


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.asarray(jax.devices()).reshape(-1), ("segs",))


def test_sharded_visible_length(mesh):
    state = build_doc()
    sharded = shard_doc_state(state, mesh)
    vis_len, _resolve, _mark = make_sharded_ops(mesh, state)
    got = int(vis_len(sharded, ALL_ACKED, -2))
    vis = np.asarray(mk._visible(state, ALL_ACKED, -2))
    want = int(np.where(vis, np.asarray(state.seg_len), 0).sum())
    assert got == want > 0


def test_sharded_resolution_matches_single_device(mesh):
    state = build_doc(n_segs=96, seg_len=3, removed_every=5)
    sharded = shard_doc_state(state, mesh)
    _len, resolve, _mark = make_sharded_ops(mesh, state)
    vis = np.asarray(mk._visible(state, ALL_ACKED, -2))
    total = int(np.where(vis, np.asarray(state.seg_len), 0).sum())
    rng = np.random.default_rng(0)
    queries = rng.integers(0, total, 64).astype(np.int32)
    gi, off = resolve(sharded, jnp.asarray(queries), ALL_ACKED, -2)
    want_i, want_o = reference_resolution(state, queries, ALL_ACKED, -2)
    np.testing.assert_array_equal(np.asarray(gi), want_i)
    np.testing.assert_array_equal(np.asarray(off), want_o)


def test_sharded_mark_range_matches(mesh):
    state = build_doc(n_segs=80, seg_len=4, removed_every=9)
    sharded = shard_doc_state(state, mesh)
    _len, _resolve, mark = make_sharded_ops(mesh, state)
    # Remove a large whole-segment range under the converged perspective.
    out = mark(sharded, 40, 200, 500, 3, ALL_ACKED, -2)
    out_np = jax.tree.map(np.asarray, jax.device_get(out))
    # Oracle: same mask math on one device.
    vis = np.asarray(mk._visible(state, ALL_ACKED, -2))
    lens = np.where(vis, np.asarray(state.seg_len), 0)
    prefix = np.cumsum(lens) - lens
    in_range = (lens > 0) & (prefix >= 40) & ((prefix + lens) <= 200)
    want_rem0 = np.where(
        (np.asarray(state.rem_keys[0]) == NO_REMOVE) & in_range,
        500, np.asarray(state.rem_keys[0]),
    )
    np.testing.assert_array_equal(out_np.rem_keys[0], want_rem0)
    assert (out_np.rem_clients[0][in_range & (want_rem0 == 500)] == 3).all()


def test_compiles_with_collectives_only_twice(mesh):
    """The resolve path lowers to exactly the designed collectives (one
    all-gather for shard totals + psums for the one-hot combine) — no
    accidental all-to-alls or resharding of the segment arrays."""
    state = build_doc()
    sharded = shard_doc_state(state, mesh)
    _len, resolve, _mark = make_sharded_ops(mesh, state)
    lowered = jax.jit(
        lambda s, q: resolve(s, q, ALL_ACKED, -2)
    ).lower(sharded, jnp.zeros(8, jnp.int32)).compile()
    text = lowered.as_text()
    assert "all-to-all" not in text
