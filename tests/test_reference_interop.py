"""Interop with reference-produced artifacts (VERDICT r3 missing #1).

Two contracts, both against files shipped INSIDE the reference checkout:

1. **Replay**: the reference's own recorded conflict-farm traces
   (`packages/dds/merge-tree/src/test/results/*.json`, the files its
   client.replay.spec.ts replays) drive our stack; every group must
   converge to the reference-computed ``resultText``.  The expected strings
   were produced by the TypeScript implementation, not by our oracle.
2. **snapshotV1**: our merge-tree summaries round-trip through the
   reference's V1 wire format (snapshotV1.ts:42 — header/body blobs,
   10k-char chunks) and a V1-loaded replica keeps converging on the rest of
   a reference trace.

Plus a literature-corpus farm mirroring the reference's beastTest
(`src/test/beastTest.spec.ts:1564` drives pp.txt through a client/server
round) to exercise multi-chunk snapshots on real text.
"""

import json
import os
import random

import pytest

from fluidframework_tpu.dds.snapshot_v1 import (
    BODY_BLOB,
    HEADER_BLOB,
    decode_snapshot_v1,
    encode_snapshot_v1,
)
from fluidframework_tpu.dds.shared_string import SharedString
from fluidframework_tpu.protocol.messages import SequencedMessage
from fluidframework_tpu.protocol.stamps import ALL_ACKED
from fluidframework_tpu.server.local_service import LocalDocument
from fluidframework_tpu.testing.reference_traces import (
    bootstrap_text,
    load_trace,
    reference_trace_files,
    replay_observer_only,
    replay_trace,
    trace_clients,
    _join_msgs,
)

TRACE_FILES = reference_trace_files()
pytestmark = pytest.mark.skipif(
    not TRACE_FILES, reason="reference checkout not present"
)

PP_TXT = "/root/reference/packages/dds/merge-tree/src/test/literature/pp.txt"


def _by_name(fragment: str) -> str:
    return next(p for p in TRACE_FILES if fragment in p)


# A representative slice for the heavier issuer-faithful replay: every
# length regime, client count, and both variants appear.
ISSUER_FILES = [
    "len_1-clients_2-default-conflict-farm-0.40",
    "len_1-clients_8-conflict-farm-with-obliterate-2.3.0",
    "len_4-clients_4-conflict-farm-with-obliterate-2.3.0",
    "len_8-clients_2-default-conflict-farm-0.40",
    "len_16-clients_4-default-conflict-farm-0.40",
    "len_32-clients_8-conflict-farm-with-obliterate-2.3.0",
    "len_64-clients_2-conflict-farm-with-obliterate-2.3.0",
    "len_128-clients_4-default-conflict-farm-0.40",
    "len_256-clients_8-default-conflict-farm-0.40",
    "len_256-clients_4-conflict-farm-with-obliterate-2.3.0",
    "len_512-clients_2-default-conflict-farm-0.40",
    "len_512-clients_8-conflict-farm-with-obliterate-2.3.0",
]


@pytest.mark.parametrize("fragment", ISSUER_FILES)
def test_issuer_faithful_replay(fragment):
    """Full client.replay.spec.ts semantics: each trace client catches up to
    the op's recorded refSeq, re-issues it locally, the sequenced message
    acks it; all replicas + a remote observer must match every group's
    reference-recorded resultText."""
    replay_trace(load_trace(_by_name(fragment)))


@pytest.mark.parametrize(
    "path", TRACE_FILES, ids=[os.path.basename(p) for p in TRACE_FILES]
)
def test_observer_replay_all_files(path):
    """Every reference trace file, applied as a pure remote stream, must
    converge to every group's reference resultText."""
    replay_observer_only(load_trace(path))


@pytest.mark.device
@pytest.mark.parametrize("fragment", [
    "len_1-clients_2-default-conflict-farm-0.40",
    "len_4-clients_4-conflict-farm-with-obliterate-2.3.0",
])
def test_kernel_observer_replay(fragment):
    """The TPU kernel behind the channel boundary consumes the reference's
    sequenced stream and converges to the reference resultText."""
    from fluidframework_tpu.dds.kernel_backend import KernelMergeTree

    replay_observer_only(
        load_trace(_by_name(fragment)),
        backend_factory=lambda: KernelMergeTree(
            max_segments=2048, remove_slots=6, prop_slots=4,
            text_capacity=16384, max_insert_len=8, ob_slots=16,
        ),
        max_groups=24,
    )


# ---------------------------------------------------------------------------
# snapshotV1 wire format
# ---------------------------------------------------------------------------

def _snapshot_after(path: str, n_groups: int):
    """Replay ``n_groups`` of a trace on an observer, then V1-encode its
    state.  Returns (groups, names, observer, blobs)."""
    groups = load_trace(path)
    names = trace_clients(groups)
    observer = replay_observer_only(groups, max_groups=n_groups)
    blobs = encode_snapshot_v1(
        observer.backend, seq=observer.current_seq,
        get_long_client_id=lambda s: names[s],
    )
    return groups, names, observer, blobs


@pytest.mark.parametrize("fragment", [
    "len_16-clients_4-default-conflict-farm-0.40",
    "len_256-clients_2-default-conflict-farm-0.40",
    "len_64-clients_2-conflict-farm-with-obliterate-2.3.0",
])
def test_snapshot_v1_roundtrip_mid_trace(fragment):
    """V1 encode -> decode reproduces the exact converged text, and the
    merge info above the MSN survives (insert/remove stamps)."""
    groups, names, observer, blobs = _snapshot_after(_by_name(fragment), 24)
    tree, seq, min_seq = decode_snapshot_v1(blobs, names.index)
    assert seq == observer.current_seq
    assert tree.visible_text(ALL_ACKED, -1) == groups[23]["resultText"]
    # Re-encoding the loaded replica reproduces the same blobs byte-for-byte
    # (encode depends only on V1-visible state, which decode preserves).
    blobs2 = encode_snapshot_v1(
        tree, seq=seq, get_long_client_id=lambda s: names[s]
    )
    assert blobs2 == blobs


def test_snapshot_v1_loaded_replica_keeps_converging():
    """A replica booted from the V1 snapshot applies the REST of the
    reference trace remotely and matches every remaining group's
    reference-recorded resultText — checkpoint/resume against the
    reference's own stream."""
    path = _by_name("len_128-clients_8-default-conflict-farm-0.40")
    groups, names, observer, blobs = _snapshot_after(path, 32)
    tree, seq, _min_seq = decode_snapshot_v1(blobs, names.index)
    loaded = SharedString(client_id="__loaded__", backend=tree)
    for join in _join_msgs(names):
        loaded.process(join)
    for gi, group in enumerate(groups[32:], start=32):
        for raw in group["msgs"]:
            loaded.process(SequencedMessage.from_json(json.dumps(raw)))
        got = tree.visible_text(ALL_ACKED, loaded.short_client)
        assert got == group["resultText"], f"group {gi} diverged after load"


def test_snapshot_v1_chunk_shape():
    """Exact reference field layout: header/body blob names, chunk fields,
    headerMetadata keys, orderedChunkMetadata (snapshotChunks.ts:49)."""
    _groups, _names, observer, blobs = _snapshot_after(
        _by_name("len_512-clients_2-default"), 24
    )
    header = json.loads(blobs[HEADER_BLOB])
    assert header["version"] == "1"
    assert set(header) == {
        "version", "segmentCount", "length", "segments", "startIndex",
        "headerMetadata",
    }
    meta = header["headerMetadata"]
    assert set(meta) == {
        "minSequenceNumber", "sequenceNumber", "orderedChunkMetadata",
        "totalLength", "totalSegmentCount",
    }
    assert meta["orderedChunkMetadata"][0] == {"id": HEADER_BLOB}
    for i, entry in enumerate(meta["orderedChunkMetadata"][1:]):
        assert entry == {"id": f"{BODY_BLOB}_{i}"}
        body = json.loads(blobs[entry["id"]])
        assert "headerMetadata" not in body
        assert body["version"] == "1"
    total = sum(
        json.loads(blobs[e["id"]])["length"]
        for e in meta["orderedChunkMetadata"]
    )
    assert total == meta["totalLength"]
    assert meta["totalLength"] == len(
        observer.backend.visible_text(ALL_ACKED, -1)
    ) + sum(  # plus still-referenceable removed-above-MSN segments
        len(s.text)
        for s in observer.backend.segments
        if s.removes and s.removes[0][0] > observer.backend.min_seq
    )


def test_snapshot_v1_loads_reference_shaped_blob():
    """A hand-built V1 snapshot in the reference's own shape (including the
    legacy singular removedClient field and a moved segment) loads into the
    oracle with the right visibility."""
    header = {
        "version": "1",
        "segmentCount": 4,
        "length": 16,
        "segments": [
            "below msn ",                                  # bare string
            {"json": {"text": "bold", "props": {"0": 1}},  # annotated
             "seq": 7, "client": "B"},
            {"json": "gone", "seq": 8, "client": "C",
             "removedSeq": 9, "removedClient": "B"},       # legacy singular
            {"json": "obbed", "seq": 6, "client": "B",
             "movedSeq": 10, "movedSeqs": [10], "movedClientIds": ["C"]},
        ],
        "startIndex": 0,
        "headerMetadata": {
            "minSequenceNumber": 5,
            "sequenceNumber": 10,
            "orderedChunkMetadata": [{"id": "header"}],
            "totalLength": 16,
            "totalSegmentCount": 4,
        },
    }
    names = ["A", "B", "C"]
    tree, seq, min_seq = decode_snapshot_v1(
        {"header": json.dumps(header)}, names.index
    )
    assert (seq, min_seq) == (10, 5)
    assert tree.visible_text(ALL_ACKED, -1) == "below msn bold"
    # Perspective BEFORE the remove was sequenced still sees "gone".
    assert tree.visible_text(8, -1) == "below msn boldgoneobbed"
    assert tree.slice_keys == {10}
    bold = tree.segments[1]
    assert bold.props == {0: (1, 0)} and bold.ins_key == 7
    assert bold.ins_client == 1


# ---------------------------------------------------------------------------
# Literature corpus (pp.txt) farm + multi-chunk snapshots
# ---------------------------------------------------------------------------

def _pp_words(n_chars: int) -> list[str]:
    with open(PP_TXT, encoding="utf-8") as f:
        text = f.read(n_chars)
    return [w for w in text.split() if w]


def test_literature_corpus_farm_and_multichunk_snapshot():
    """beastTest-style corpus run: seed a document with a pp.txt slice, have
    4 clients make word-granular concurrent edits through the sequencer,
    converge, then prove the multi-chunk (>10k chars) V1 snapshot
    round-trips the full state."""
    words = _pp_words(50_000)
    seed_text = " ".join(words[:5200])
    assert len(seed_text) > 25_000  # forces >=2 body chunks

    doc = LocalDocument("pp")
    clients = []
    for i in range(4):
        c = SharedString(client_id=f"w{i}")
        doc.connect(c.client_id, c.process)
        clients.append(c)
    doc.process_all()
    for rep in clients:
        bootstrap_text(rep.backend, seed_text)

    rng = random.Random(7)
    for _round in range(12):
        for c in clients:
            n = len(c.text)
            for _ in range(rng.randint(1, 3)):
                kind = rng.random()
                if kind < 0.55 or n < 64:
                    w = rng.choice(words)
                    pos = rng.randint(0, n)
                    c.insert_text(pos, w + " ")
                    n += len(w) + 1
                elif kind < 0.85:
                    p1 = rng.randint(0, n - 32)
                    c.remove_range(p1, p1 + rng.randint(1, 24))
                    n -= 0  # approximate; next op re-reads len
                else:
                    p1 = rng.randint(0, n - 32)
                    c.annotate_range(p1, p1 + 16, 0, rng.randint(1, 9))
                n = len(c.text)
            for m in c.take_outbox():
                doc.submit(m)
        doc.process_all()
    texts = {c.text for c in clients}
    assert len(texts) == 1

    src = clients[0]
    blobs = encode_snapshot_v1(
        src.backend, seq=src.current_seq,
        get_long_client_id=lambda s: f"w{s}",
    )
    n_bodies = sum(1 for k in blobs if k.startswith(BODY_BLOB))
    assert n_bodies >= 2, "corpus snapshot must overflow into body chunks"
    # Each non-final chunk crossed the 10k threshold with its last segment.
    for name, raw in blobs.items():
        chunk = json.loads(raw)
        if chunk["startIndex"] + chunk["segmentCount"] < json.loads(
            blobs[HEADER_BLOB]
        )["headerMetadata"]["totalSegmentCount"]:
            assert chunk["length"] >= 10_000

    tree, _seq, _min_seq = decode_snapshot_v1(
        blobs, lambda name: int(name[1:])
    )
    assert tree.visible_text(ALL_ACKED, -1) == src.text
    # Annotations survive (values; stamps are V1-dropped by design).
    orig = src.backend.annotations(ALL_ACKED, src.short_client)
    loaded = tree.annotations(ALL_ACKED, -1)
    assert [sorted(d.items()) for d in orig] == [
        sorted(d.items()) for d in loaded
    ]


@pytest.mark.skipif(
    not os.environ.get("FFTPU_ALL_TRACES"),
    reason="full issuer-faithful sweep is opt-in (FFTPU_ALL_TRACES=1)",
)
@pytest.mark.parametrize(
    "path", TRACE_FILES, ids=[os.path.basename(p) for p in TRACE_FILES]
)
def test_issuer_faithful_replay_all_files(path):
    """Opt-in exhaustive form of the issuer-faithful replay: every one of
    the reference's 60 recorded files, full length (~75s total)."""
    replay_trace(load_trace(path))
