"""Read fan-out plane (ISSUE 13): encode-once delta frames, bounded
drop-and-resync byte-identity, the snapshot-boot historian tier's HTTP
caching contract, and sequencer-free at-most-once presence."""

from __future__ import annotations

import http.client
import json
import socket
import threading
import time

import pytest

from fluidframework_tpu.fanout import (
    FLAVOR_ENVELOPE,
    FLAVOR_WIRE,
    RESYNC_BOOT_MARKER,
    FanoutPlane,
    HistorianTier,
)
from fluidframework_tpu.protocol.messages import (
    UnsequencedMessage,
    wire_encode_count,
)
from fluidframework_tpu.server.sequencer import Sequencer


def _mint(n_ops: int, client: str = "w0", text: str = "x") -> list:
    """Sequenced messages via a real sequencer: join + n_ops ops."""
    seqr = Sequencer()
    out = [seqr.join(client)]
    for i in range(n_ops):
        out.append(seqr.ticket(UnsequencedMessage(
            client_id=client, client_seq=i + 1, ref_seq=out[-1].seq,
            contents={"i": i, "text": text * (i % 5 + 1)},
        )))
    return out


def _oracle(msgs) -> bytes:
    return b"".join(m.wire_line() for m in msgs)


# --------------------------------------------------------------------------
# Delta frames: encode-once, shared bytes
# --------------------------------------------------------------------------

def test_broadcaster_frames_encode_once_shared():
    """N frame subscribers + the firehose oracle share ONE encode per
    message — one frame per (doc, pump), the same object for everyone."""
    from fluidframework_tpu.server.ordered_log import Topic
    from fluidframework_tpu.server.lambdas import BroadcasterLambda

    deltas = Topic("deltas", 1)
    bc = BroadcasterLambda(deltas, 0)
    got: list[list] = [[] for _ in range(8)]
    for i in range(8):
        bc.subscribe_frames("d", lambda fr, i=i: got[i].append(fr))
    msgs = _mint(24)
    before = wire_encode_count()
    for chunk in (msgs[:10], msgs[10:]):  # two pumps
        for m in chunk:
            deltas.produce("d", m)
        bc.pump()
    encodes = wire_encode_count() - before
    # <=1 encode per message however many subscribers (the oracle below
    # re-reads the SAME cached bytes: no further encodes).
    assert encodes == len(msgs)
    assert bc.frames_built == 2
    for sub in got:
        assert len(sub) == 2
        # every subscriber got the SAME frame objects
        assert sub[0] is got[0][0] and sub[1] is got[0][1]
    assert b"".join(fr.wire for fr in got[0]) == _oracle(msgs)
    assert wire_encode_count() - before == len(msgs)


def test_plane_publish_and_drain_byte_identity():
    """Wire + envelope subscribers over several pumps: every observed
    stream byte-identical to its flavor's oracle."""
    plane = FanoutPlane()
    msgs = _mint(40)
    plane.ensure_doc("d", last_seq=0)
    sinks = []
    for flavor in (FLAVOR_WIRE, FLAVOR_WIRE, FLAVOR_ENVELOPE):
        chunks: list[bytes] = []
        peer = plane.new_peer(sink=chunks.append)
        plane.attach("d", peer, flavor=flavor, last_seq=0)
        sinks.append((flavor, peer, chunks))
    for lo in range(0, len(msgs), 7):
        plane.publish("d", msgs[lo:lo + 7])
    for _flavor, peer, _chunks in sinks:
        plane.drain_virtual(peer)
    wire_oracle = _oracle(msgs)
    env_oracle = b"".join(m.op_envelope() for m in msgs)
    for flavor, _peer, chunks in sinks:
        want = wire_oracle if flavor == FLAVOR_WIRE else env_oracle
        assert b"".join(chunks) == want
    assert plane.stats()["frames_published"] == len(range(0, len(msgs), 7))
    assert plane.stats()["resyncs"] == 0


def test_slow_subscriber_drop_and_resync_byte_identity():
    """A subscriber that stops draining falls off the bounded ring; its
    resync rebuilds the missed range from the log — the full observed
    stream stays byte-identical to the firehose oracle, and the fast
    subscriber never noticed."""
    msgs = _mint(60)
    log = {m.seq: m for m in msgs}

    def resync_source(doc_id, from_seq):
        return [m for s, m in sorted(log.items()) if s > from_seq]

    plane = FanoutPlane(resync_source=resync_source, ring_frames=4)
    plane.ensure_doc("d", last_seq=0)
    fast_chunks: list[bytes] = []
    slow_chunks: list[bytes] = []
    fast = plane.new_peer(sink=fast_chunks.append)
    slow = plane.new_peer(sink=slow_chunks.append)
    plane.attach("d", fast, flavor=FLAVOR_WIRE, last_seq=0)
    plane.attach("d", slow, flavor=FLAVOR_WIRE, last_seq=0)
    for lo in range(0, 30, 3):
        plane.publish("d", msgs[lo:lo + 3])
        plane.drain_virtual(fast)  # fast keeps up pump by pump
    # slow drains only now: >4 frames published, the ring evicted some.
    plane.drain_virtual(slow)
    # tail pumps: both keep up again
    for lo in range(30, len(msgs), 3):
        plane.publish("d", msgs[lo:lo + 3])
        plane.drain_virtual(fast)
    plane.drain_virtual(slow)
    oracle = _oracle(msgs)
    assert b"".join(fast_chunks) == oracle
    assert b"".join(slow_chunks) == oracle
    stats = plane.stats()
    assert stats["frames_evicted"] > 0
    assert slow.resyncs >= 1 and fast.resyncs == 0
    assert stats["boot_resyncs"] == 0


def test_resync_without_retained_log_sends_boot_marker():
    """When the missed range is no longer retained, the subscriber gets
    the snapshot-boot marker instead of silently missing bytes, and the
    live stream resumes after it."""
    msgs = _mint(24)
    plane = FanoutPlane(resync_source=lambda d, s: None, ring_frames=2)
    plane.ensure_doc("d", last_seq=0)
    chunks: list[bytes] = []
    peer = plane.new_peer(sink=chunks.append)
    plane.attach("d", peer, flavor=FLAVOR_WIRE, last_seq=0)
    for lo in range(0, 20, 2):
        plane.publish("d", msgs[lo:lo + 2])
    plane.drain_virtual(peer)
    # Everything missed collapses into the marker: the subscriber must
    # snapshot-boot (historian tier) instead of receiving a gapped stream.
    assert chunks == [RESYNC_BOOT_MARKER]
    assert plane.stats()["boot_resyncs"] == 1
    # post-marker pumps stream normally again
    plane.publish("d", msgs[20:22])
    plane.publish("d", msgs[22:24])
    plane.drain_virtual(peer)
    assert b"".join(chunks[1:]) == _oracle(msgs[20:24])


# --------------------------------------------------------------------------
# Historian snapshot-boot tier
# --------------------------------------------------------------------------

@pytest.fixture
def historian_store():
    from fluidframework_tpu.server.gitstore import GitSnapshotStore

    store = GitSnapshotStore()
    store.save(10, {"root": {"a": "v1", "big": {"x": 1, "y": 2}}})
    store.save(20, {"root": {"a": "v2", "big": {"x": 1, "y": 2}}})
    tier = HistorianTier(lambda doc: store if doc == "doc" else None).start()
    yield tier, store
    tier.stop()


def _get(port: int, path: str, headers: dict | None = None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path, headers=headers or {})
    r = conn.getresponse()
    body = r.read()
    out = (r.status, dict(r.getheaders()), body)
    conn.close()
    return out


def test_historian_latest_etag_and_304(historian_store):
    tier, store = historian_store
    status, headers, body = _get(tier.port, "/doc/doc/snapshot")
    assert status == 200
    latest_sha = store.versions[-1][1]
    assert headers["ETag"] == f'"{latest_sha}"'
    assert headers["Cache-Control"] == "no-cache"
    payload = json.loads(body)
    assert payload["commit"] == latest_sha and payload["seq"] == 20
    assert payload["summary"]["root"]["a"] == "v2"
    # Conditional revalidation: one header round-trip, no body.
    status, headers, body = _get(
        tier.port, "/doc/doc/snapshot",
        headers={"If-None-Match": f'"{latest_sha}"'},
    )
    assert status == 304 and body == b""
    assert headers["ETag"] == f'"{latest_sha}"'
    # A stale ETag (older version) still gets the full new snapshot.
    old_sha = store.versions[0][1]
    status, _h, body = _get(
        tier.port, "/doc/doc/snapshot",
        headers={"If-None-Match": f'"{old_sha}"'},
    )
    assert status == 200 and json.loads(body)["commit"] == latest_sha
    stats = tier.stats()
    assert stats["not_modified_304"] == 1 and stats["cold_serves"] == 2


def test_historian_sha_addressed_immutable_and_versions(historian_store):
    tier, store = historian_store
    old_sha = store.versions[0][1]
    status, headers, body = _get(tier.port, f"/doc/doc/snapshot/{old_sha}")
    assert status == 200
    assert "immutable" in headers["Cache-Control"]
    assert json.loads(body)["summary"]["root"]["a"] == "v1"
    # sha-addressed conditional GET: 304 without touching the store
    status, _h, body = _get(
        tier.port, f"/doc/doc/snapshot/{old_sha}",
        headers={"If-None-Match": f'"{old_sha}"'},
    )
    assert status == 304 and body == b""
    status, _h, body = _get(tier.port, "/doc/doc/versions?max=5")
    ids = [v["id"] for v in json.loads(body)["versions"]]
    assert ids == [store.versions[1][1], store.versions[0][1]]
    status, _h, _b = _get(tier.port, "/doc/doc/snapshot/deadbeef")
    assert status == 404


def test_historian_partial_subtree_read_over_http(historian_store):
    tier, store = historian_store
    sha = store.versions[-1][1]
    status, headers, body = _get(
        tier.port, f"/doc/doc/path/{sha}?path=root/big"
    )
    assert status == 200
    assert json.loads(body)["value"] == {"x": 1, "y": 2}
    assert "immutable" in headers["Cache-Control"]
    status, _h, body = _get(tier.port, f"/doc/doc/path/{sha}?path=root/a")
    assert json.loads(body)["value"] == "v1" or json.loads(body)["value"] == "v2"
    status, _h, _b = _get(tier.port, f"/doc/doc/path/{sha}?path=root/nope")
    assert status == 404
    assert tier.stats()["path_reads"] == 2


def test_historian_serves_service_docs_without_touching_sequencer():
    """ServicePlane integration: boots come straight from the gitstore —
    unknown docs 404 (never instantiated), and reads leave the sequencer
    exactly where it was."""
    from fluidframework_tpu.server.netserver import ServicePlane

    plane = ServicePlane(historian_port=0).start()
    try:
        with plane.nexus.lock:
            doc = plane.service.document("hot")
            doc.save_snapshot(5, {"ch": {"v": 1}})
            seq_before = doc.sequencer.seq
        port = plane.historian.port
        status, headers, body = _get(port, "/doc/hot/snapshot")
        assert status == 200
        sha = json.loads(body)["commit"]
        status, _h, _b = _get(
            port, "/doc/hot/snapshot", headers={"If-None-Match": f'"{sha}"'}
        )
        assert status == 304
        status, _h, _b = _get(port, "/doc/never-created/snapshot")
        assert status == 404
        with plane.nexus.lock:
            assert plane.service.peek_document("never-created") is None
            assert doc.sequencer.seq == seq_before
    finally:
        plane.stop()


# --------------------------------------------------------------------------
# Presence plane
# --------------------------------------------------------------------------

def test_presence_at_most_once_bounded_drop_no_sequencer():
    """Signals encode once, deliver at most once per subscriber, drop past
    the per-peer bound, and never touch any ordering state."""
    plane = FanoutPlane(max_directs=4)
    plane.ensure_doc("d", last_seq=0)
    live_chunks: list[bytes] = []
    live = plane.new_peer(sink=live_chunks.append)
    stalled = plane.new_peer(sink=lambda b: None)
    plane.add_signal_peer("d", live)
    plane.add_signal_peer("d", stalled)
    before = wire_encode_count()
    for i in range(10):
        plane.publish_signal("d", "w0", {"cursor": i})
        plane.drain_virtual(live)  # live keeps up; stalled never drains
    assert wire_encode_count() == before  # signals never touch op encodes
    got = [json.loads(c) for c in live_chunks]
    assert [g["contents"]["cursor"] for g in got] == list(range(10))
    assert all(g["t"] == "signal" and g["clientId"] == "w0" for g in got)
    stats = plane.stats()
    # stalled peer: bound 4, ten published -> six shed, at most once each
    assert stats["signal_drops"] == 6 and stalled.signal_drops == 6
    assert stats["signals_published"] == 10
    assert stats["frames_published"] == 0  # nowhere near the ordering path


def test_stalled_signal_subscriber_does_not_stall_ticketing():
    """ISSUE 13 satellite regression: a signal subscriber that never reads
    must not stall op ticketing.  Pre-fanout, submit_signal wrote every
    subscriber's socket synchronously under the service lock — one full
    kernel buffer wedged the whole ordering plane."""
    from fluidframework_tpu.server.netserver import ServicePlane

    plane = ServicePlane().start()
    stalled = writer = None
    try:
        # Stalled subscriber: connects with signals, then never reads.
        stalled = socket.create_connection(("127.0.0.1", plane.nexus.port))
        stalled.sendall(json.dumps({
            "t": "connect", "doc": "d", "client": "lurker",
            "mode": "read", "signals": True,
        }).encode() + b"\n")
        sf = stalled.makefile("rb")
        while b'"joined"' not in sf.readline():
            pass  # connect fully processed; from here the lurker stalls
        # Tight per-peer signal bound so the storm sheds visibly (kernel
        # buffers on loopback can otherwise swallow megabytes).
        with plane.nexus.lock:
            plane.nexus.fanout.max_directs = 64
        # Writer client on its own socket.
        writer = socket.create_connection(("127.0.0.1", plane.nexus.port))
        writer.sendall(json.dumps({
            "t": "connect", "doc": "d", "client": "w0", "mode": "write",
        }).encode() + b"\n")
        wf = writer.makefile("rb")
        while b'"joined"' not in wf.readline():
            pass
        # Saturate far past any kernel buffer: ~32MB of signal payload the
        # stalled peer never drains.  Old code would block mid-loop.
        blob = "s" * 65536
        t0 = time.monotonic()
        for i in range(500):
            writer.sendall(json.dumps(
                {"t": "signal", "content": {"i": i, "blob": blob}}
            ).encode() + b"\n")
        # Ticketing stays live: an op submitted and sync-echoed promptly.
        writer.sendall(json.dumps({
            "t": "submit",
            "msg": {"clientId": "w0", "clientSequenceNumber": 1,
                    "referenceSequenceNumber": 1, "type": "op",
                    "contents": {"probe": True}},
        }).encode() + b"\n")
        writer.sendall(b'{"t": "sync", "n": 7}\n')
        writer.settimeout(30)
        deadline = time.monotonic() + 30
        synced = False
        while time.monotonic() < deadline:
            line = wf.readline()
            if not line:
                break
            if b'"sync"' in line and b'"n": 7' in line:
                synced = True
                break
        elapsed = time.monotonic() - t0
        assert synced, "ticketing wedged behind the stalled signal subscriber"
        assert elapsed < 30
        stats = plane.http.service_stats()["fanout"]
        # the stalled peer's bounded queue shed most of the storm
        assert stats["signal_drops"] > 0
        with plane.nexus.lock:
            doc = plane.service.peek_document("d")
            # signals never sequenced: log = lurker-less quorum traffic only
            types = [m.type for m in doc.sequencer.log]
            assert "signal" not in types
    finally:
        for s in (stalled, writer):
            if s is not None:
                s.close()
        plane.stop()


# --------------------------------------------------------------------------
# Wire integration: consumers + clients share frames over real TCP
# --------------------------------------------------------------------------

def _read_lines_until(sock_file, n_payload_lines: int, deadline_s: float = 30):
    out = []
    end = time.monotonic() + deadline_s
    while len(out) < n_payload_lines and time.monotonic() < end:
        line = sock_file.readline()
        if not line:
            break
        out.append(line)
    return out


def test_firehose_and_clients_share_one_encode_over_tcp():
    """One connect client + two firehose consumers on one doc: per pump,
    every sequenced message is wire-encoded exactly once, and each
    consumer's byte stream equals the log's cached encoding."""
    from fluidframework_tpu.server.netserver import ServicePlane

    plane = ServicePlane().start()
    socks = []
    try:
        consumers = []
        for _ in range(2):
            c = socket.create_connection(("127.0.0.1", plane.nexus.port))
            socks.append(c)
            c.sendall(b'{"t": "consume", "doc": "d"}\n')
            f = c.makefile("rb")
            assert b"consuming" in f.readline()
            consumers.append(f)
        w = socket.create_connection(("127.0.0.1", plane.nexus.port))
        socks.append(w)
        w.sendall(json.dumps({
            "t": "connect", "doc": "d", "client": "w0", "mode": "write",
        }).encode() + b"\n")
        wf = w.makefile("rb")
        while b'"joined"' not in wf.readline():
            pass
        # Quiesce the join broadcast (its one encode included) before
        # snapshotting the counter: the sync echo orders after the frame.
        w.sendall(b'{"t": "sync", "n": 0}\n')
        while True:
            line = wf.readline()
            if not line or b'"sync"' in line:
                break
        before = wire_encode_count()
        n_ops = 16
        for i in range(n_ops):
            w.sendall(json.dumps({
                "t": "submit",
                "msg": {"clientId": "w0", "clientSequenceNumber": i + 1,
                        "referenceSequenceNumber": 1, "type": "op",
                        "contents": {"i": i}},
            }).encode() + b"\n")
        w.sendall(b'{"t": "sync", "n": 1}\n')
        while True:
            line = wf.readline()
            if not line or b'"sync"' in line:
                break
        # join already encoded pre-`before`; the 16 ops encode once each
        # though three subscribers (2 wire + 1 envelope) observed them.
        assert wire_encode_count() - before == n_ops
        with plane.nexus.lock:
            doc = plane.service.peek_document("d")
            oracle = b"".join(m.wire_line() for m in doc.sequencer.log)
        for f in consumers:
            lines = _read_lines_until(f, len(oracle.splitlines()))
            assert b"".join(lines) == oracle
    finally:
        for s in socks:
            s.close()
        plane.stop()


def test_pipelined_sync_disconnect_still_echoes():
    """A client may pipeline sync + disconnect in one write: the sync echo
    (its deterministic quiescence marker) must reach the wire before the
    server tears the session down — queued-writer delivery included."""
    from fluidframework_tpu.server.netserver import ServicePlane

    plane = ServicePlane().start()
    s = None
    try:
        s = socket.create_connection(("127.0.0.1", plane.nexus.port))
        s.sendall(json.dumps({
            "t": "connect", "doc": "d", "client": "w0", "mode": "write",
        }).encode() + b"\n")
        f = s.makefile("rb")
        while b'"joined"' not in f.readline():
            pass
        s.sendall(b'{"t": "sync", "n": 9}\n{"t": "disconnect"}\n')
        s.settimeout(15)
        saw_sync = False
        while True:
            line = f.readline()
            if not line:
                break  # server closed after the goodbye
            if b'"sync"' in line and b'"n": 9' in line:
                saw_sync = True
        assert saw_sync, "sync echo lost on pipelined disconnect"
    finally:
        if s is not None:
            s.close()
        plane.stop()


def test_backlogged_consumer_resyncs_over_tcp_byte_identical():
    """A consumer that stops reading while the ring is tiny gets dropped
    to catch-up and resynced from the log — the bytes it finally reads are
    still exactly the firehose oracle."""
    from fluidframework_tpu.server.netserver import ServicePlane

    plane = ServicePlane().start()
    socks = []
    try:
        with plane.nexus.lock:
            plane.nexus.fanout.ring_frames = 4  # force eviction quickly
        c = socket.create_connection(("127.0.0.1", plane.nexus.port))
        socks.append(c)
        c.sendall(b'{"t": "consume", "doc": "d"}\n')
        cf = c.makefile("rb")
        assert b"consuming" in cf.readline()
        w = socket.create_connection(("127.0.0.1", plane.nexus.port))
        socks.append(w)
        w.sendall(json.dumps({
            "t": "connect", "doc": "d", "client": "w0", "mode": "write",
        }).encode() + b"\n")
        wf = w.makefile("rb")
        while b'"joined"' not in wf.readline():
            pass
        # Big payloads + no reads on the consumer: kernel buffers fill,
        # frames fall off the 4-deep ring.
        blob = "y" * 32768
        n_ops = 96
        for i in range(n_ops):
            w.sendall(json.dumps({
                "t": "submit",
                "msg": {"clientId": "w0", "clientSequenceNumber": i + 1,
                        "referenceSequenceNumber": 1, "type": "op",
                        "contents": {"i": i, "blob": blob}},
            }).encode() + b"\n")
        w.sendall(b'{"t": "sync", "n": 2}\n')
        while True:
            line = wf.readline()
            if not line or b'"sync"' in line:
                break
        with plane.nexus.lock:
            oracle = b"".join(
                m.wire_line()
                for m in plane.service.peek_document("d").sequencer.log
            )
        got = b""
        c.settimeout(10)
        end = time.monotonic() + 60
        while len(got) < len(oracle) and time.monotonic() < end:
            try:
                data = c.recv(1 << 20)
            except socket.timeout:
                break
            if not data:
                break
            got += data
        assert got == oracle
    finally:
        for s in socks:
            s.close()
        plane.stop()


# --------------------------------------------------------------------------
# Client boot-marker handling (PR 14): FleetConsumer snapshot-boot resync
# --------------------------------------------------------------------------

def _force_boot_marker(plane, doc_id: str):
    """Drive the REAL resync path into its boot branch for every socket
    subscriber of ``doc_id``: the retained window is declared compacted
    away (resync source empty) and each peer's floor is dropped below it —
    exactly the state a long-stalled consumer wakes up to.  The eviction
    mechanics themselves are covered by the server-side tests
    (test_resync_without_retained_log_sends_boot_marker and the backlogged
    TCP test); this helper makes the CLIENT contract testable without
    megabytes of filler traffic."""
    fanout = plane.nexus.fanout
    with plane.nexus.lock:
        fanout._resync_source = lambda _d, _s: None
        peers = [p for p in fanout._docs[doc_id].subs if p.is_socket]
    with fanout._lock:
        for p in peers:
            p.sub.last_seq = -1
    for p in peers:
        fanout.resync(p)  # no locks held: the resync-source contract
    plane.nexus.fanout_writer.wake(peers)
    return peers


def test_fleet_consumer_boot_marker_snapshot_resync_over_tcp(tmp_path):
    """End-to-end over real TCP: a FleetConsumer whose firehose fell off
    the retained log receives ``{"t":"resync","boot":true}``, fetches the
    latest historian snapshot over HTTP, adopts it into the engine, and
    re-consumes from its seq — the device doc converges byte-identically
    with the writers despite the gap (ops the ring skipped)."""
    from fluidframework_tpu.dds.shared_string import SharedString
    from fluidframework_tpu.models.doc_batch_engine import DocBatchEngine
    from fluidframework_tpu.native.ingest_native import available
    from fluidframework_tpu.server.fleet_consumer import FleetConsumer
    from fluidframework_tpu.server.netserver import ServicePlane
    from fluidframework_tpu.server.ordered_log import CheckpointStore

    if not available():
        pytest.skip("native ingest encoder unavailable")

    plane = ServicePlane(historian_port=0).start()
    fc = None
    try:
        with plane.nexus.lock:
            doc = plane.service.document("d0")
            writers = []
            for w in range(2):
                c = SharedString(client_id=f"d0-w{w}")
                doc.connect(c.client_id, c.process)
                writers.append(c)
            doc.process_all()
        a, b = writers

        def flush():
            n = 0
            with plane.nexus.lock:
                d = plane.service.document("d0")
                for c in writers:
                    for m in c.take_outbox():
                        d.submit(m)
                        n += 1
                d.process_all()
            return n

        a.insert_text(0, "hello ")
        rows = flush()
        b.insert_text(6, "world")
        rows += flush()

        def mk_engine():
            return DocBatchEngine(
                1, max_segments=4096, text_capacity=1 << 16,
                max_insert_len=8, ops_per_step=8, use_mesh=False,
                recovery="off", doc_keys=["d0"],
            )

        eng = mk_engine()
        fc = FleetConsumer(
            "127.0.0.1", plane.nexus.port, eng, ["d0"],
            historian=("127.0.0.1", plane.historian.port),
        )
        fc.run_for(rows)
        assert eng.text(0) == a.text

        # The consumer stalls while writers keep editing: these ops form
        # the range the ring will have evicted by the time it wakes.
        for _ in range(6):
            a.insert_text(0, "gap-")
            flush()

        # An acked summary covering the WHOLE log so far reaches the
        # historian (the scribe's job in production) — built here by an
        # oracle engine replaying the sequencer log.
        oracle = mk_engine()
        with plane.nexus.lock:
            log_msgs = list(plane.service.document("d0").sequencer.log)
        # Object-path replay: the record must carry the quorum table the
        # adopted consumer resumes with (native-mode quorum lives in C++).
        for m in log_msgs:
            oracle.ingest(0, m)
        oracle.step()
        oracle.checkpoint_store = CheckpointStore(str(tmp_path / "ck"))
        oracle.maybe_checkpoint(force=True)
        rec = oracle.checkpoint_store.load("d0")
        assert rec is not None and rec["engine"] == "doc_batch"
        snap_seq = oracle.hosts[0].last_seq
        assert snap_seq > eng.hosts[0].last_seq  # a real gap to adopt over
        with plane.nexus.lock:
            plane.service.document("d0").save_snapshot(snap_seq, rec)

        _force_boot_marker(plane, "d0")

        deadline = time.monotonic() + 30
        while fc.boot_resyncs == 0 and time.monotonic() < deadline:
            fc.pump(wait_s=0.05)
            fc.step()
            assert not fc.dead_socks, "boot resync failed (doc marked dead)"
        assert fc.boot_resyncs == 1
        assert eng.counters.get("boot_snapshots_adopted") == 1
        assert eng.hosts[0].last_seq >= snap_seq

        # Post-resync the stream is live again: new edits converge.
        a.insert_text(0, "post-")
        flush()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            fc.pump(wait_s=0.05)
            fc.step()
            if eng.text(0) == a.text:
                break
        assert eng.text(0) == a.text == b.text
        assert not eng.errors().any()
        assert fc.health()["boot_resyncs"] == 1
        assert fc.health()["boot_resync_failures"] == 0
    finally:
        if fc is not None:
            fc.close()
        plane.stop()


def test_fleet_consumer_boot_resync_refused_below_floor(tmp_path):
    """Refusal half of the boot-resync contract: when the only historian
    snapshot sits at/below the doc's applied floor, adoption is REFUSED —
    re-subscribing from the engine's own floor would just draw another
    boot marker (an infinite resync loop that looks healthy) — and the
    doc falls to the supervisor restart path: ``boot_resync_failures``
    counts, ``dead_socks`` carries the doc, and the engine's served state
    is untouched."""
    from fluidframework_tpu.dds.shared_string import SharedString
    from fluidframework_tpu.models.doc_batch_engine import DocBatchEngine
    from fluidframework_tpu.native.ingest_native import available
    from fluidframework_tpu.server.fleet_consumer import FleetConsumer
    from fluidframework_tpu.server.netserver import ServicePlane
    from fluidframework_tpu.server.ordered_log import CheckpointStore

    if not available():
        pytest.skip("native ingest encoder unavailable")

    plane = ServicePlane(historian_port=0).start()
    fc = None
    try:
        with plane.nexus.lock:
            doc = plane.service.document("d0")
            a = SharedString(client_id="d0-w0")
            doc.connect(a.client_id, a.process)
            doc.process_all()

        def flush():
            n = 0
            with plane.nexus.lock:
                d = plane.service.document("d0")
                for m in a.take_outbox():
                    d.submit(m)
                    n += 1
                d.process_all()
            return n

        a.insert_text(0, "hello world")
        rows = flush()

        def mk_engine():
            return DocBatchEngine(
                1, max_segments=4096, text_capacity=1 << 16,
                max_insert_len=8, ops_per_step=8, use_mesh=False,
                recovery="off", doc_keys=["d0"],
            )

        eng = mk_engine()
        fc = FleetConsumer(
            "127.0.0.1", plane.nexus.port, eng, ["d0"],
            historian=("127.0.0.1", plane.historian.port),
        )
        fc.run_for(rows)
        assert eng.text(0) == a.text
        text_before = eng.text(0)

        # A perfectly well-formed snapshot record, but stamped at/below
        # the doc's applied floor (the historian's seq stamp is the
        # authoritative one): stale — nothing for the consumer to adopt.
        oracle = mk_engine()
        with plane.nexus.lock:
            log_msgs = list(plane.service.document("d0").sequencer.log)
        for m in log_msgs:
            oracle.ingest(0, m)
        oracle.step()
        oracle.checkpoint_store = CheckpointStore(str(tmp_path / "ck"))
        oracle.maybe_checkpoint(force=True)
        rec = oracle.checkpoint_store.load("d0")
        assert rec is not None
        snap_seq = eng.hosts[0].last_seq  # == the floor: refused
        with plane.nexus.lock:
            plane.service.document("d0").save_snapshot(snap_seq, rec)

        _force_boot_marker(plane, "d0")

        deadline = time.monotonic() + 30
        while not fc.dead_socks and time.monotonic() < deadline:
            fc.pump(wait_s=0.05)
            fc.step()
        assert 0 in fc.dead_socks, "doc should fall to the supervisor path"
        assert fc.boot_resyncs == 0
        assert fc.boot_resync_failures == 1
        assert fc.health()["boot_resync_failures"] == 1
        assert eng.counters.get("boot_snapshots_stale") == 1
        assert not eng.counters.get("boot_snapshots_adopted")
        # The refusal never touched the served doc.
        assert eng.text(0) == text_before
        assert not eng.errors().any()
    finally:
        if fc is not None:
            fc.close()
        plane.stop()


def test_delta_connection_surfaces_boot_marker():
    """Driver side of the contract: NetworkDeltaConnection hands the boot
    marker to the host's boot listener (the container reload hook) instead
    of silently dropping the line."""
    from fluidframework_tpu.driver.network_driver import (
        NetworkDocumentServiceFactory,
    )
    from fluidframework_tpu.server.netserver import ServicePlane

    plane = ServicePlane().start()
    try:
        booted = []
        factory = NetworkDocumentServiceFactory(
            "127.0.0.1", plane.nexus.port, plane.http.port
        )
        svc = factory.create_document_service("d0")
        conn = svc.connect_to_delta_stream(
            "c0", lambda _m: None, boot_listener=lambda: booted.append(1)
        )
        try:
            _force_boot_marker(plane, "d0")
            deadline = time.monotonic() + 10
            while not booted and time.monotonic() < deadline:
                conn.pump(block_s=0.05)
            assert booted and conn.boot_resyncs == 1
        finally:
            conn.disconnect()
    finally:
        plane.stop()
