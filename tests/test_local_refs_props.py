"""Local references + rich properties on the string channel, both backends.

Local references (ref merge-tree localReference.ts:232): per-replica
positions that follow the text through local and remote edits, sliding to
the range start when their containing range is removed.

Rich properties (ref PropertiesManager): arbitrary keys and JSON values,
interned to int ids for the columnar backends; wire ops and summaries
carry the raw forms, so replicas with different interning orders stay
byte-identical where it matters.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService

pytestmark = pytest.mark.usefixtures("string_backend")


def _fleet(n=2):
    svc = LocalService()
    doc = svc.document("d")
    rts = []
    for i in range(n):
        rt = ContainerRuntime(default_registry(), container_id=f"c{i}")
        rt.create_datastore("root").create_channel("sharedString", "t")
        rt.connect(doc, f"c{i}")
        rts.append(rt)
    doc.process_all()
    return doc, rts


def _ch(rt):
    return rt.datastore("root").get_channel("t")


def _sync(doc, rts):
    for rt in rts:
        rt.flush()
    doc.process_all()


# ------------------------------------------------------------ local references

def test_reference_follows_remote_edits():
    doc, (a, b) = _fleet()
    _ch(a).insert_text(0, "hello world")
    _sync(doc, (a, b))
    ref = _ch(a).create_local_reference(6)  # before "world"
    assert _ch(a).text[ref.position :].startswith("world")

    _ch(b).insert_text(0, "XX ")
    _sync(doc, (a, b))
    assert _ch(a).text[ref.position :].startswith("world")

    _ch(b).remove_range(0, 3)
    _sync(doc, (a, b))
    assert _ch(a).text[ref.position :].startswith("world")


def test_reference_slides_on_containing_remove():
    doc, (a, b) = _fleet()
    _ch(a).insert_text(0, "abcdef")
    _sync(doc, (a, b))
    ref = _ch(a).create_local_reference(3)  # at "d"
    _ch(b).remove_range(2, 5)  # removes "cde" containing the anchor
    _sync(doc, (a, b))
    assert _ch(a).text == "abf"
    assert ref.position == 2  # slid to the removed range's start


def test_reference_with_local_pending_edits():
    doc, (a, b) = _fleet()
    _ch(a).insert_text(0, "abcd")
    _sync(doc, (a, b))
    ref = _ch(a).create_local_reference(2)
    _ch(a).insert_text(0, "zz")  # pending local edit shifts the local view
    assert ref.position == 4
    _sync(doc, (a, b))
    assert ref.position == 4


def test_reference_remove():
    doc, (a, _b) = _fleet()
    _ch(a).insert_text(0, "abc")
    ref = _ch(a).create_local_reference(1)
    ref.remove()
    with pytest.raises(AssertionError):
        _ = ref.position


# ------------------------------------------------------------ rich properties

def test_rich_values_converge_across_replicas():
    doc, (a, b) = _fleet()
    _ch(a).insert_text(0, "styled text")
    _sync(doc, (a, b))
    _ch(a).annotate_range(0, 6, "style", {"bold": True, "size": 12})
    _ch(b).annotate_range(3, 9, "author", "user-b")
    _sync(doc, (a, b))
    assert _ch(a).annotations() == _ch(b).annotations()
    ann = _ch(a).annotations()
    assert ann[0] == {"style": {"bold": True, "size": 12}}
    assert ann[4] == {"style": {"bold": True, "size": 12}, "author": "user-b"}
    assert ann[8] == {"author": "user-b"}


def test_rich_props_lww_and_summary_round_trip():
    doc, (a, b) = _fleet()
    _ch(a).insert_text(0, "abc")
    _sync(doc, (a, b))
    # Different interning orders on each replica: a interns "x" first, b
    # interns "y" first — raw-form summaries must still agree.
    _ch(a).annotate_range(0, 2, "x", [1, 2])
    _sync(doc, (a, b))
    _ch(b).annotate_range(1, 3, "y", None)
    _ch(b).annotate_range(0, 1, "x", [9])  # later write wins
    _sync(doc, (a, b))
    sa, sb = _ch(a).summarize(), _ch(b).summarize()
    assert sa == sb
    assert _ch(a).annotations()[0] == {"x": [9]}

    # A loading replica resolves the summarized raw forms.
    rt = ContainerRuntime(default_registry(), container_id="late")
    rt.create_datastore("root").create_channel("sharedString", "t")
    rt.connect(doc, "late")
    doc.process_all()
    assert _ch(rt).annotations() == _ch(a).annotations()
    # And keeps collaborating with rich values.
    _ch(rt).annotate_range(0, 3, "style", {"em": True})
    _sync(doc, (a, b, rt))
    assert _ch(rt).annotations() == _ch(a).annotations() == _ch(b).annotations()


def test_rich_props_survive_reconnect_regeneration():
    doc, (a, b) = _fleet()
    _ch(a).insert_text(0, "abcdef")
    _sync(doc, (a, b))
    _ch(a).annotate_range(1, 5, "mark", {"kind": "comment", "id": 7})
    a.flush()
    _ch(b).insert_text(3, "XY")  # concurrent: splits the annotate range
    b.flush()
    a.disconnect()
    doc.process_all()
    a.connect(doc, "c0.r1")
    doc.process_all()
    assert _ch(a).annotations() == _ch(b).annotations()
    assert _ch(a).annotations()[1] == {"mark": {"kind": "comment", "id": 7}}
