"""SharedTree moves, transactions, and compressed revision ids.

Move marks (ref feature-libraries/sequence-field moveOut/moveIn): apply in
both directions, invert round-trip, codec, and the rebase laws — including
the follow-the-move rule (a concurrent Modify/Remove targets the node at
its move destination) and the sided convergence square fuzz with moves in
the mix.

Transactions (ref shared-tree Transactor): all-or-nothing commits over the
channel stack.  Id-compression (ref id-compressor op-space discipline):
edits ship op-space revision ids plus creation ranges; replicas finalize in
total order; summaries carry stable UUIDs.
"""

from __future__ import annotations

import random

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.dds.tree.changeset import (
    Insert,
    Modify,
    MoveIn,
    MoveOut,
    NodeChange,
    Remove,
    Skip,
    apply_node_change,
    change_from_json,
    change_to_json,
    clone_change,
    invert_node_change,
    make_insert,
    make_move,
    make_remove,
    make_set_value,
    rebase_node_change,
)
from fluidframework_tpu.dds.tree.forest import Node
from fluidframework_tpu.dds.tree.schema import leaf
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService


def num_array(*vals):
    root = Node(type="__root__")
    root.fields[""] = [leaf(v) for v in vals]
    return root


def values(root):
    return [n.value for n in root.fields[""]]


def apply_root(root, change):
    apply_node_change(root, change)


def converge(start_vals, a, b):
    f1 = num_array(*start_vals)
    apply_root(f1, clone_change(a))
    apply_root(f1, rebase_node_change(clone_change(b), a, a_after=True))
    f2 = num_array(*start_vals)
    apply_root(f2, clone_change(b))
    apply_root(f2, rebase_node_change(clone_change(a), b, a_after=False))
    return values(f1), values(f2)


# ---------------------------------------------------------------- apply

def test_move_right_and_left():
    f = num_array(0, 1, 2, 3, 4)
    apply_root(f, make_move([], "", 0, 2, 4))  # [0,1] to boundary 4
    assert values(f) == [2, 3, 0, 1, 4]
    f = num_array(0, 1, 2, 3, 4)
    apply_root(f, make_move([], "", 3, 2, 1))  # [3,4] to boundary 1
    assert values(f) == [0, 3, 4, 1, 2]


def test_move_identity_and_invert():
    f = num_array(0, 1, 2)
    mv = make_move([], "", 1, 1, 1)
    apply_root(f, mv)
    assert values(f) == [0, 1, 2]

    f = num_array(0, 1, 2, 3)
    mv = make_move([], "", 0, 2, 3)
    applied = clone_change(mv)
    apply_root(f, applied)
    assert values(f) == [2, 0, 1, 3]
    apply_root(f, invert_node_change(applied))
    assert values(f) == [0, 1, 2, 3]


def test_move_codec_roundtrip():
    mv = make_move([], "", 1, 2, 5)
    assert change_to_json(change_from_json(change_to_json(mv))) == change_to_json(mv)


# ---------------------------------------------------------------- rebase

def test_modify_follows_move():
    """b moves the node a modifies: a's modify lands at the destination."""
    a = make_set_value([("", 0)], 99)
    b = make_move([], "", 0, 1, 3)
    v1, v2 = converge([0, 1, 2], a, b)
    assert v1 == v2 == [1, 2, 99]


def test_remove_follows_move():
    a = make_remove([], "", 0, 1)
    b = make_move([], "", 0, 2, 3)
    v1, v2 = converge([0, 1, 2], a, b)
    assert v1 == v2 == [2, 1]


def test_move_of_concurrently_removed_nodes_shrinks():
    """b removes part of the range a moves: only survivors move."""
    a = make_move([], "", 0, 3, 4)
    b = make_remove([], "", 1, 1)
    v1, v2 = converge([0, 1, 2, 3], a, b)
    assert v1 == v2 == [3, 0, 2]


def test_insert_at_moved_gap_stays_at_source():
    """a inserts at a boundary inside the range b moved away: the insert
    lands in the gap left at the source (deterministic contract)."""
    a = make_insert([], "", 1, [leaf(99)])
    b = make_move([], "", 0, 2, 4)
    v1, v2 = converge([0, 1, 2, 3], a, b)
    assert v1 == v2


def test_move_vs_move_square():
    a = make_move([], "", 0, 1, 3)
    b = make_move([], "", 2, 1, 0)
    v1, v2 = converge([0, 1, 2], a, b)
    assert v1 == v2


def test_rebase_square_fuzz_with_moves():
    """The sided convergence square with moves in the random mix — the
    multimark fuzz of test_tree_changeset extended with MoveOut/MoveIn."""

    def rand_marks(rng: random.Random, n: int, tag: int) -> list:
        marks, pos, v = [], 0, 0
        mid = tag * 1000
        while pos < n:
            r = rng.random()
            if r < 0.25:
                k = rng.randint(1, n - pos)
                marks.append(Skip(k)); pos += k
            elif r < 0.4:
                k = rng.randint(1, n - pos)
                marks.append(Remove(k)); pos += k
            elif r < 0.55:
                v += 1
                marks.append(Insert([leaf(tag * 100 + v)]))
            elif r < 0.7:
                marks.append(Modify(NodeChange(value=(tag * 1000 + pos,)))); pos += 1
            elif r < 0.85:
                # A move pair: out here, in at a random later boundary.
                k = rng.randint(1, n - pos)
                mid += 1
                marks.append(MoveOut(k, mid))
                pos += k
                gap = rng.randint(0, n - pos)
                if gap:
                    marks.append(Skip(gap))
                    pos += gap
                marks.append(MoveIn(mid, k))
            else:
                break
        return marks

    for seed in range(3000):
        rng = random.Random(seed)
        n = rng.randint(0, 6)
        a = NodeChange(fields={"": rand_marks(rng, n, 1)})
        b = NodeChange(fields={"": rand_marks(rng, n, 2)})
        v1, v2 = converge(list(range(n)), a, b)
        assert v1 == v2, (
            f"seed {seed}: {change_to_json(a)} vs {change_to_json(b)}: "
            f"{v1} != {v2}"
        )


def test_split_move_invert_roundtrip():
    """b removes the middle of the range a moves: rebased a carries split
    pieces (discontiguous original offsets); applying it and its inverse
    must restore the post-b state exactly."""
    a = make_move([], "", 0, 3, 4)
    b = make_remove([], "", 1, 1)
    f = num_array(0, 1, 2, 3)
    apply_root(f, clone_change(b))
    after_b = values(f)
    a2 = rebase_node_change(clone_change(a), b, a_after=True)
    applied = clone_change(a2)
    apply_root(f, applied)
    assert values(f) == [3, 0, 2]
    apply_root(f, invert_node_change(applied))
    assert values(f) == after_b


def test_move_invert_roundtrip_fuzz():
    for seed in range(200):
        rng = random.Random(seed)
        n = rng.randint(1, 6)
        src = rng.randint(0, n - 1)
        cnt = rng.randint(1, n - src)
        dst = rng.randint(0, n)
        f = num_array(*range(n))
        before = values(f)
        mv = make_move([], "", src, cnt, dst)
        applied = clone_change(mv)
        apply_root(f, applied)
        apply_root(f, invert_node_change(applied))
        assert values(f) == before, f"seed {seed}"


# ------------------------------------------------------------- channel stack

def _fleet(n=2):
    svc = LocalService()
    doc = svc.document("d")
    rts = []
    for i in range(n):
        rt = ContainerRuntime(default_registry(), container_id=f"c{i}")
        rt.create_datastore("root").create_channel("sharedTree", "t")
        rt.connect(doc, f"c{i}")
        rts.append(rt)
    doc.process_all()
    return doc, rts


def _tree(rt):
    return rt.datastore("root").get_channel("t")


def _sync(doc, rts):
    for rt in rts:
        rt.flush()
    doc.process_all()


def test_transaction_atomic_commit():
    doc, (a, b) = _fleet()
    ta, tb = _tree(a), _tree(b)
    for i in range(3):
        ta.submit_change(make_insert([], "", i, [leaf(i)]))
    _sync(doc, (a, b))

    with ta.transaction():
        ta.submit_change(make_insert([], "", 3, [leaf(30)]))
        ta.submit_change(make_set_value([("", 0)], 100))
        ta.submit_change(make_remove([], "", 1, 1))
    # Concurrent edit on b before it sees the transaction.
    tb.submit_change(make_insert([], "", 0, [leaf(7)]))
    _sync(doc, (a, b))
    assert ta.forest.to_json() == tb.forest.to_json()
    vals = [n.value for n in ta.forest.root_field]
    assert 30 in vals and 100 in vals and 1 not in vals and 7 in vals


def test_transaction_abort_rolls_back():
    doc, (a, b) = _fleet()
    ta = _tree(a)
    ta.submit_change(make_insert([], "", 0, [leaf(1)]))
    _sync(doc, (a, b))
    before = ta.forest.to_json()
    with pytest.raises(ValueError):
        with ta.transaction():
            ta.submit_change(make_insert([], "", 1, [leaf(2)]))
            ta.submit_change(make_set_value([("", 0)], 9))
            raise ValueError("abort")
    assert ta.forest.to_json() == before
    _sync(doc, (a, b))
    assert ta.forest.to_json() == _tree(b).forest.to_json() == before


def test_transaction_with_moves_converges():
    doc, (a, b) = _fleet()
    ta, tb = _tree(a), _tree(b)
    for i in range(5):
        ta.submit_change(make_insert([], "", i, [leaf(i)]))
    _sync(doc, (a, b))
    with ta.transaction():
        ta.submit_change(make_move([], "", 0, 2, 5))
        ta.submit_change(make_set_value([("", 4)], 77))
    tb.submit_change(make_move([], "", 2, 1, 0))
    _sync(doc, (a, b))
    assert ta.forest.to_json() == tb.forest.to_json()


def test_revision_ids_are_compressed_and_summaries_stable():
    doc, (a, b) = _fleet()
    ta, tb = _tree(a), _tree(b)
    ta.submit_change(make_insert([], "", 0, [leaf(1)]))
    tb.submit_change(make_insert([], "", 0, [leaf(2)]))
    _sync(doc, (a, b))
    # Wire revisions are ints (op-space), not UUID strings.
    assert all(isinstance(t.revision[1], int) for t in ta.em.trunk)
    # Both replicas finalized both sessions' ranges in the same total
    # order: decompressed stable ids agree.
    sa = ta.summarize()
    sb = tb.summarize()
    assert sa["editManager"] == sb["editManager"]
    for t in sa["editManager"]["trunk"]:
        assert isinstance(t["rev"], str) and len(t["rev"]) == 36  # stable uuid

    # A fresh replica loads the summary and keeps collaborating.
    rt = ContainerRuntime(default_registry(), container_id="late")
    rt.create_datastore("root").create_channel("sharedTree", "t")
    rt.connect(doc, "late")
    doc.process_all()
    tc = _tree(rt)
    assert tc.forest.to_json() == ta.forest.to_json()
    tc.submit_change(make_insert([], "", 0, [leaf(3)]))
    _sync(doc, (a, b, rt))
    assert tc.forest.to_json() == ta.forest.to_json() == tb.forest.to_json()


def test_slice_movein_rebase_keeps_offsets():
    """A changeset with multiple slice MoveIns of one id (the inverse of a
    split move — what redo revertibles hold) must survive rebase: each
    slice keeps its own offset/count instead of collapsing to the full
    register (review regression)."""
    # Build the inverse-of-split-move shape directly: nodes [X, Y] sit at
    # positions 0,1 (the moved block); the change returns X to offset 0
    # (position 3) and Y to offset 2 (position 4) of the original layout.
    change = NodeChange(
        fields={
            "": [
                MoveOut(1, 7, 0),
                MoveOut(1, 7, 2),
                Skip(1),
                MoveIn(7, 1, 0),
                MoveIn(7, 1, 2),
            ]
        }
    )
    f = num_array(10, 20, 30)
    apply_root(f, clone_change(change))
    assert values(f) == [30, 10, 20]
    # Rebase over an unrelated insert at the front: slices must persist.
    b = make_insert([], "", 0, [leaf(99)])
    rebased = rebase_node_change(clone_change(change), b, a_after=True)
    f = num_array(10, 20, 30)
    apply_root(f, b)
    apply_root(f, rebased)
    assert values(f) == [99, 30, 10, 20]


def test_move_farm_converges():
    """Randomized 3-client farm over the full container stack with moves in
    the mix: partial delivery, pending bridges, and EditManager chains —
    the schedule shapes the pairwise square fuzz cannot reach (this is what
    caught the split-move register-order bug)."""
    for seed in range(60):
        rng = random.Random(seed)
        doc, rts = _fleet(3)
        trees = [_tree(rt) for rt in rts]
        for _step in range(40):
            ci = rng.randrange(3)
            t = trees[ci]
            n = len(t.forest.root_field)
            kind = rng.choices(["ins", "rm", "move", "set"], [5, 3, 4, 2])[0]
            if kind == "ins" or n == 0:
                t.submit_change(
                    make_insert([], "", rng.randint(0, n), [leaf(rng.randrange(100))])
                )
            elif kind == "rm":
                i = rng.randrange(n)
                t.submit_change(make_remove([], "", i, rng.randint(1, min(2, n - i))))
            elif kind == "move":
                s = rng.randrange(n)
                c = rng.randint(1, min(2, n - s))
                t.submit_change(make_move([], "", s, c, rng.randint(0, n)))
            else:
                t.submit_change(
                    make_set_value([("", rng.randrange(n))], rng.randrange(100))
                )
            if rng.random() < 0.4:
                rts[ci].flush()
            if rng.random() < 0.3:
                doc.process_some(rng.randint(0, doc.pending_count))
        _sync(doc, rts)
        jsons = [t.forest.to_json() for t in trees]
        assert all(j == jsons[0] for j in jsons), f"seed {seed} diverged"


def test_rollback_returns_id_range():
    doc, (a, b) = _fleet()
    ta = _tree(a)
    ta.submit_change(make_insert([], "", 0, [leaf(1)]))
    _sync(doc, (a, b))
    # Stage an edit and roll it back before flushing; then ship another
    # edit — its id range must still finalize cleanly everywhere.
    ta.submit_change(make_insert([], "", 1, [leaf(2)]))
    a.rollback_staged()
    ta.submit_change(make_insert([], "", 1, [leaf(3)]))
    _sync(doc, (a, b))
    assert ta.forest.to_json() == _tree(b).forest.to_json()
    assert [n.value for n in ta.forest.root_field] == [1, 3]
