"""Framework utilities: dependency synthesizer, request routing, and the
agent scheduler.

Mirrors the reference's packages/framework/synthesize (DependencyContainer
optional/required synthesis + parent fallback), request-handler
(RuntimeRequestHandlerBuilder + stock handlers), and agent-scheduler
(exclusive pick/release with worker handoff and leader election)."""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.framework import (
    AgentScheduler,
    DependencyContainer,
    RuntimeRequestHandlerBuilder,
    datastore_request_handler,
)
from fluidframework_tpu.framework.request_handler import (
    create_fluid_object_handler,
    default_route_handler,
)
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService


# ----------------------------------------------------------------- synthesize

def test_dependency_container_required_optional():
    dc = DependencyContainer()
    dc.register("logger", {"name": "log"})
    calls = []

    def factory():
        calls.append(1)
        return {"made": True}

    dc.register("service", factory)
    s = dc.synthesize(optional=["missing", "logger"], required=["service"])
    assert s.logger == {"name": "log"}
    assert s.missing is None
    assert s.service == {"made": True}
    # factories memoize
    dc.synthesize(required=["service"])
    assert calls == [1]
    with pytest.raises(KeyError):
        dc.synthesize(required=["absent"])
    with pytest.raises(ValueError):
        dc.register("logger", {})


def test_dependency_container_parent_chain():
    parent = DependencyContainer()
    parent.register("shared", "from-parent")
    child = DependencyContainer(parent)
    child.register("local", 42)
    assert child.has("shared") and not child.has("shared", exclude_parents=True)
    s = child.synthesize(required=["shared", "local"])
    assert s.shared == "from-parent" and s.local == 42
    assert child.registered_types == ["local"]


# ------------------------------------------------------------ request handler

def make_runtime():
    svc = LocalService()
    doc = svc.document("d")
    c = ContainerRuntime(default_registry(), container_id="A")
    ds = c.create_datastore("root")
    ds.create_channel("sharedString", "text")
    c.connect(doc, "A")
    doc.process_all()
    return svc, doc, c


def test_request_routing():
    svc, doc, c = make_runtime()
    route = (
        RuntimeRequestHandlerBuilder()
        .push(
            default_route_handler("root"),
            create_fluid_object_handler({"health": {"ok": True}}),
            datastore_request_handler,
        )
        .build()
    )
    assert route("/", c)["value"] is c.datastore("root")
    assert route("/health", c)["value"] == {"ok": True}
    assert route("/root", c)["value"] is c.datastore("root")
    ch = route("/root/text", c)
    assert ch["status"] == 200 and ch["value"].channel_type == "sharedString"
    assert route("/nope/deep/path", c)["status"] == 404
    assert route("/root/missing", c)["status"] == 404


def test_request_parser_unescapes():
    from fluidframework_tpu.framework import RequestParser

    p = RequestParser("/a%20b/c", {"h": 1})
    assert p.path_parts == ["a b", "c"]
    assert p.sub_request(1).path_parts == ["c"]
    # sub_request never re-decodes: encoded '/' and literal '%' survive.
    p2 = RequestParser("/ds/a%2Fb/file%2520name")
    assert p2.path_parts == ["ds", "a/b", "file%20name"]
    assert p2.sub_request(1).path_parts == ["a/b", "file%20name"]


# ------------------------------------------------------------- agent scheduler

def scheduler_pair():
    svc = LocalService()
    doc = svc.document("d")

    def mk(name):
        c = ContainerRuntime(default_registry(), container_id=name)
        ds = c.create_datastore("root")
        ds.create_channel("taskManager", "tasks")
        c.connect(doc, name)
        return c

    a, b = mk("A"), mk("B")
    doc.process_all()
    ta = a.datastore("root").get_channel("tasks")
    tb = b.datastore("root").get_channel("tasks")
    return svc, doc, a, b, AgentScheduler(ta), AgentScheduler(tb)


def test_exclusive_pick_and_handoff_on_release():
    svc, doc, a, b, sa, sb = scheduler_pair()
    events = []
    sa.pick("index", lambda: events.append("A-start"), lambda: events.append("A-lost"))
    sb.pick("index", lambda: events.append("B-start"), lambda: events.append("B-lost"))
    a.flush(); b.flush(); doc.process_all()
    # Exactly one runs.
    assert events == ["A-start"]
    assert sa.picked_tasks() == ["index"] and sb.picked_tasks() == []
    # Release hands off to the queued volunteer.
    sa.release("index")
    a.flush(); doc.process_all()
    assert events == ["A-start", "B-start"]
    assert sb.picked_tasks() == ["index"] and sa.picked_tasks() == []


def test_handoff_on_client_leave():
    svc, doc, a, b, sa, sb = scheduler_pair()
    ran = []
    sa.pick("job", lambda: ran.append("A"))
    sb.pick("job", lambda: ran.append("B"))
    a.flush(); b.flush(); doc.process_all()
    assert ran == ["A"]
    a.disconnect()
    doc.process_all()
    assert ran == ["A", "B"]
    assert sb.picked_tasks() == ["job"]


def test_leader_election_and_takeover():
    svc, doc, a, b, sa, sb = scheduler_pair()
    log = []
    sa.volunteer_for_leadership(lambda: log.append("A-lead"), lambda: log.append("A-deposed"))
    sb.volunteer_for_leadership(lambda: log.append("B-lead"))
    a.flush(); b.flush(); doc.process_all()
    assert log == ["A-lead"]
    assert sa.is_leader and not sb.is_leader
    assert sb.leader == "A"
    a.disconnect()
    doc.process_all()
    assert log == ["A-lead", "B-lead"]
    assert sb.is_leader


def test_reconnect_re_volunteers_picked_tasks():
    """A reconnect under a new identity evicts the old id from the queues;
    the scheduler must re-volunteer so picked tasks are never lost."""
    svc, doc, a, b, sa, sb = scheduler_pair()
    ran = []
    sa.pick("job", lambda: ran.append("A"), lambda: ran.append("A-lost"))
    sb.pick("job", lambda: ran.append("B"))
    a.flush(); b.flush(); doc.process_all()
    assert ran == ["A"]
    # A reconnects under a fresh identity: loses the task to B...
    a.disconnect()
    doc.process_all()
    a.connect(doc, "A2")
    a.flush(); doc.process_all()
    # Replica listener ordering may interleave A-lost and B-start.
    assert ran[0] == "A" and sorted(ran[1:]) == ["A-lost", "B"]
    # ...but is queued again, so when B releases, A (as A2) takes over.
    sb.release("job")
    b.flush(); doc.process_all()
    assert ran[-1] == "A" and len(ran) == 4
    assert sa.picked_tasks() == ["job"]


def test_completed_task_is_not_resurrected():
    """complete() clears the queue for good: no scheduler may re-volunteer
    the finished task (the DDS docstring's contract)."""
    svc, doc, a, b, sa, sb = scheduler_pair()
    ran = []
    sa.pick("build", lambda: ran.append("A"))
    sb.pick("build", lambda: ran.append("B"))
    a.flush(); b.flush(); doc.process_all()
    assert ran == ["A"]
    ta = a.datastore("root").get_channel("tasks")
    ta.complete("build")
    a.flush(); doc.process_all()
    assert ran == ["A"], "completed task re-ran a worker"
    assert sa.picked_tasks() == [] and sb.picked_tasks() == []
    tb = b.datastore("root").get_channel("tasks")
    assert ta.assignee("build") is None and tb.assignee("build") is None
    assert not ta.queues.get("build") and not tb.queues.get("build")


def test_inflight_volunteer_after_complete_leaves_no_zombie():
    """B's volunteer is in flight when A completes the task: the DDS's
    completion tombstone drops the stale volunteer (authored before seeing
    the completion), so no assignee ever exists without a worker and later
    picks are not blocked."""
    svc, doc, a, b, sa, sb = scheduler_pair()
    ran = []
    sa.pick("build", lambda: ran.append("A"))
    a.flush(); doc.process_all()
    sb.pick("build", lambda: ran.append("B"))  # volunteer NOT yet flushed
    ta = a.datastore("root").get_channel("tasks")
    ta.complete("build")
    a.flush()
    doc.process_all()  # the COMPLETE sequences before B's volunteer
    b.flush()
    doc.process_all()  # B's stale volunteer is dropped by the tombstone
    tb = b.datastore("root").get_channel("tasks")
    assert ta.assignee("build") is None and tb.assignee("build") is None
    assert ran == ["A"]
    # The task id is free for a fresh round of picks.
    sa.pick("build", lambda: ran.append("A2"))
    a.flush(); doc.process_all()
    assert ran == ["A", "A2"]


def test_completer_can_restart_its_own_task_immediately():
    """complete() then volunteer() back-to-back from the assignee is a
    deliberate restart — exempt from the tombstone drop."""
    svc, doc, a, b, sa, sb = scheduler_pair()
    ta = a.datastore("root").get_channel("tasks")
    ta.volunteer("job")
    a.flush(); doc.process_all()
    assert ta.assigned("job")
    ta.complete("job")
    ta.volunteer("job")
    a.flush(); doc.process_all()
    tb = b.datastore("root").get_channel("tasks")
    assert ta.assignee("job") == "A" and tb.assignee("job") == "A"


def test_replayed_volunteer_dropped_after_completion():
    """A pending volunteer replayed across a reconnect must not resurrect a
    task completed while the client was away (the fresh wire ref_seq would
    blind the sequenced tombstone check; the channel drops it at resubmit
    using the authored refSeq)."""
    svc, doc, a, b, sa, sb = scheduler_pair()
    ta = a.datastore("root").get_channel("tasks")
    tb = b.datastore("root").get_channel("tasks")
    ta.volunteer("job")
    a.flush(); doc.process_all()
    tb.volunteer("job")  # pending, then B drops before it sequences
    b.disconnect()
    doc.process_all()
    ta.complete("job")
    a.flush(); doc.process_all()
    b.connect(doc, "B2")
    b.flush(); doc.process_all()
    tb2 = b.datastore("root").get_channel("tasks")
    assert ta.assignee("job") is None and tb2.assignee("job") is None


def test_resubmit_tombstone_contract():
    """The resubmit rules for volunteers against a live tombstone (the
    unclean-drop replay path, where the wire ref_seq is re-stamped):
    stale replays drop, restart-flagged replays go through, and
    metadata-less (stash-rehydrated) replays conservatively drop."""
    svc, doc, a, b, sa, sb = scheduler_pair()
    ta = a.datastore("root").get_channel("tasks")
    ta.volunteer("job")
    a.flush(); doc.process_all()
    ta.complete("job")
    a.flush(); doc.process_all()
    assert "job" in ta.completed_at
    tomb_seq = ta.completed_at["job"][0]
    op = {"type": "volunteer", "taskId": "job"}

    def settle():
        a.flush(); doc.process_all()
        return ta.assignee("job")

    # Stale replay: authored before the completion, no restart flag.
    ta.resubmit(op, {"ref": tomb_seq - 1})
    assert settle() is None
    # Stash-rehydrated replay (metadata lost): conservatively stale.
    ta.resubmit(op, None)
    assert settle() is None
    # Completer's own pre-ack restart: exempt via the restart flag.
    ta.resubmit(op, {"ref": tomb_seq - 1, "restart": True})
    assert settle() == "A"
    ta.abandon("job")
    assert settle() is None
    # Post-completion volunteer (authored at/after the completion): through.
    ta.resubmit(op, {"ref": tomb_seq})
    assert settle() == "A"


def test_presence_dispose_unregisters():
    from fluidframework_tpu.framework import ContainerSchema, Presence
    from fluidframework_tpu.framework.service_client import LocalServiceClient

    client = LocalServiceClient()
    schema = ContainerSchema(initial_objects={"text": "sharedString"})
    fc, _ = client.create_container(schema, "pdoc")
    client.service.process_all()
    runtime = fc.container.runtime
    before = len(runtime.member_left_listeners)
    ps = [Presence(fc.container) for _ in range(3)]
    assert len(runtime.member_left_listeners) == before + 3
    for p in ps:
        p.dispose()
    assert len(runtime.member_left_listeners) == before


def test_data_object_lifecycle_and_handles():
    """Aqueduct lifecycle hooks (initializingFirstTime on create only,
    initializingFromExisting on load only, hasInitialized after both) and
    handle round-trip: a handle stored in one object's root map resolves
    to the target object on another replica."""
    from fluidframework_tpu.framework.aqueduct import (
        DataObjectFactory,
        is_handle,
        resolve_handle,
    )

    svc = LocalService()
    doc = svc.document("d")
    calls = []

    def first_time(o):
        calls.append("first")
        o.root.set("title", "untitled")

    factory = DataObjectFactory(
        "note",
        initial_channels={"text": "sharedString"},
        initializing_first_time=first_time,
        initializing_from_existing=lambda o: calls.append("existing"),
        has_initialized=lambda o: calls.append("has"),
    )

    def mk(name):
        c = ContainerRuntime(default_registry(), container_id=name)
        c.connect(doc, name)
        return c

    a, b = mk("A"), mk("B")
    doc.process_all()
    note = factory.create(a, "note1")
    linker = factory.create(a, "note2")
    linker.root.set("link", note.handle)
    linker.root.set("textLink", note.channel_handle("text"))
    a.flush()
    doc.process_all()
    assert calls[:2] == ["first", "has"]

    note_b = factory.get(b, "note1")
    assert calls[-2:] == ["existing", "has"]
    assert note_b.root.get("title") == "untitled"
    # Handle resolution on the OTHER replica.
    linker_b = factory.get(b, "note2")
    h = linker_b.root.get("link")
    assert is_handle(h)
    resolved = resolve_handle(b, h)
    assert resolved.id == "note1" and resolved.root.get("title") == "untitled"
    ch = resolve_handle(b, linker_b.root.get("textLink"))
    assert ch.channel_type == "sharedString"
    with pytest.raises(KeyError):
        resolve_handle(b, {"__fluid_handle__": "/nope"})
    with pytest.raises(TypeError):
        resolve_handle(b, {"__fluid_handle__": None})
    # GC sees dict-shaped handles: note1 is reachable via note2's map.
    from fluidframework_tpu.runtime.gc import scan_handles

    ds_refs, blob_refs = set(), set()
    scan_handles(b.summarize(), ds_refs, blob_refs)
    assert "note1" in ds_refs


def test_double_pick_rejected():
    svc, doc, a, b, sa, sb = scheduler_pair()
    sa.pick("t", lambda: None)
    with pytest.raises(ValueError):
        sa.pick("t", lambda: None)
    with pytest.raises(ValueError):
        sa.release("never-picked")
