"""Megastep pipeline: K-invariance fuzz + dispatch-amortization counters.

The tentpole contract of the scan-fused megastep dispatch
(ops/mergetree_kernel.apply_megastep / ops/tree_kernel.apply_nested_megastep
behind models/doc_batch_engine / models/tree_batch_engine):

- **K-invariance**: an identical op schedule applied with megastep_k=1
  (today's per-slice dispatch, preserved exactly) and megastep_k=8 produces
  BYTE-IDENTICAL device states and digests for both engine families —
  including obliterate ops (the per-slice ob gate hoisted to the scan
  carry), overflow-latch recovery into grow lanes, quarantine/readmit
  interleaving, and tree fallback routing.
- **Counters**: ``steps_per_dispatch`` / ``megastep_k`` /
  ``staging_overlap_packs`` surface through ``health()`` and the fleet
  status line (``fleet_main.status_snapshot``), and a megastep engine
  actually amortizes (steps_per_dispatch > 1 on deep queues).

Tier-1 sizes here; the larger sweep runs under ``-m slow``.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from fluidframework_tpu.models.doc_batch_engine import DocBatchEngine, _fleet_digest
from fluidframework_tpu.models.tree_batch_engine import TreeBatchEngine
from fluidframework_tpu.server.fleet_main import status_snapshot

from test_engine_checkpoint import _ins, _join, _op, _rm


# ------------------------------------------------------------------ schedule

def _schedule(
    n_docs: int,
    rounds: int,
    seed: int = 0,
    obliterate: bool = False,
    poison: tuple | None = None,
    big: tuple | None = None,
):
    """Deterministic single-writer schedule (valid in its own perspective):
    inserts/removes, optional plain obliterates, one optional poison op
    (out-of-range insert -> quarantine) and one optional capacity-buster
    (long insert -> overflow latch + grow-lane recovery)."""
    rng = np.random.default_rng(seed)
    lengths = [0] * n_docs
    seqs = [0] * n_docs
    out: list[tuple[int, object]] = []
    for r in range(rounds):
        for d in range(n_docs):
            if poison == (d, r):
                seqs[d] += 1
                out.append((d, _ins(seqs[d], 10**6, "XX")))
            seqs[d] += 1
            roll = rng.random()
            if big == (d, r):
                p = lengths[d] // 2
                out.append((d, _ins(seqs[d], p, "Z" * 40)))
                lengths[d] += 40
            elif obliterate and lengths[d] >= 6 and roll < 0.15:
                p1 = int(rng.integers(0, lengths[d] - 2))
                p2 = int(rng.integers(p1 + 1, lengths[d] + 1))
                out.append((d, _op(seqs[d], {"type": 4, "pos1": p1, "pos2": p2})))
                lengths[d] -= p2 - p1
            elif lengths[d] >= 4 and roll < 0.4:
                p = int(rng.integers(0, lengths[d] - 1))
                out.append((d, _rm(seqs[d], p, p + 1)))
                lengths[d] -= 1
            else:
                p = int(rng.integers(0, lengths[d] + 1))
                out.append((d, _ins(seqs[d], p, "ab")))
                lengths[d] += 2
    return out


def _run_doc_engine(megastep_k, schedule, n_docs, step_every=41, **kw):
    kw.setdefault("max_segments", 128)
    kw.setdefault("text_capacity", 1024)
    eng = DocBatchEngine(
        n_docs, remove_slots=4, max_insert_len=8, ops_per_step=4,
        use_mesh=False, megastep_k=megastep_k, **kw,
    )
    for d in range(n_docs):
        eng.ingest(d, _join("w0", 0))
    for i, (d, msg) in enumerate(schedule):
        eng.ingest(d, msg)
        if (i + 1) % step_every == 0:
            eng.step()
    eng.step()
    return eng


def _assert_identical(a: DocBatchEngine, b: DocBatchEngine) -> None:
    """Byte-identical device states + digests + views + lane routing."""
    for x, y in zip(jax.tree.leaves(a.state), jax.tree.leaves(b.state)):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes()
    assert (
        np.asarray(_fleet_digest(a.state)).tobytes()
        == np.asarray(_fleet_digest(b.state)).tobytes()
    )
    assert sorted(a.overflow) == sorted(b.overflow)
    for d in a.overflow:
        assert a.overflow[d].geometry == b.overflow[d].geometry
        for x, y in zip(
            jax.tree.leaves(a.overflow[d].state),
            jax.tree.leaves(b.overflow[d].state),
        ):
            assert np.asarray(x).tobytes() == np.asarray(y).tobytes()
    assert sorted(a.quarantine) == sorted(b.quarantine)
    assert sorted(a.oracles) == sorted(b.oracles)
    for d in range(a.n_docs):
        assert a.text(d) == b.text(d), f"doc {d}"
        assert a.annotations(d) == b.annotations(d), f"doc {d}"
    assert not a.errors().any() and not b.errors().any()


# ------------------------------------------------------- string K-invariance

@pytest.mark.parametrize("seed", [0, 1])
def test_k_invariance_doc_engine(seed):
    sched = _schedule(6, 24, seed=seed)
    a = _run_doc_engine(1, sched, 6)
    b = _run_doc_engine(8, sched, 6)
    # The megastep engine must actually have fused slices (otherwise this
    # test proves nothing).
    assert b.health()["steps_per_dispatch"] > 1.0
    _assert_identical(a, b)


def test_k_invariance_with_obliterates():
    # n_docs=6 matches the plain-invariance tests so the module-level jit
    # cache serves every (geometry, K) program already compiled there.
    sched = _schedule(6, 24, seed=2, obliterate=True)
    assert any(m.contents.get("type") == 4 for _d, m in sched)
    a = _run_doc_engine(1, sched, 6)
    b = _run_doc_engine(8, sched, 6)
    assert b.health()["steps_per_dispatch"] > 1.0
    _assert_identical(a, b)


def test_k_invariance_overflow_latch_recovery():
    """A capacity-busting insert latches ERR_* on device and recovers into
    a grow lane at the same observation point (megastep granularity) for
    K=1 and K=8 — states, lanes, and grown geometries all byte-identical."""
    # Geometry chosen so ONLY the capacity-buster overflows (text, not
    # segments) and one doubling fits the replay — exactly one grow-lane
    # geometry to compile, keeping the test tier-1-cheap.
    sched = _schedule(4, 20, seed=3, big=(1, 4))
    kw = dict(max_segments=64, text_capacity=48)
    a = _run_doc_engine(1, sched, 4, **kw)
    b = _run_doc_engine(8, sched, 4, **kw)
    assert a.overflow or a.oracles, "schedule must actually overflow"
    _assert_identical(a, b)
    assert a.health()["capacity_recoveries"] == b.health()["capacity_recoveries"]


def test_k_invariance_quarantine_readmit_interleaving():
    """A poison op quarantines its doc mid-schedule; backoff readmission
    packs the oracle state back into the batch while traffic continues —
    identical under K=1 and K=8 (readmit cadence counts step() calls,
    which are K-invariant)."""
    sched = _schedule(6, 24, seed=4, poison=(2, 5))
    kw = dict(readmit_after_steps=2)
    a = _run_doc_engine(1, sched, 6, step_every=5, **kw)
    b = _run_doc_engine(8, sched, 6, step_every=5, **kw)
    ha, hb = a.health(), b.health()
    assert ha["quarantines"] == hb["quarantines"] >= 1
    assert ha.get("readmissions", 0) == hb.get("readmissions", 0) >= 1
    _assert_identical(a, b)


@pytest.mark.slow
@pytest.mark.parametrize("seed", range(6))
def test_k_invariance_sweep(seed):
    """Larger fuzz sweep: more docs/rounds, obliterates + poison + overflow
    in one schedule, several K values."""
    sched = _schedule(
        12, 48, seed=seed, obliterate=True, poison=(3, 9), big=(5, 7)
    )
    kw = dict(max_segments=32, text_capacity=256, readmit_after_steps=3)
    ref = _run_doc_engine(1, sched, 12, step_every=11, **kw)
    for k in (2, 4, 8):
        eng = _run_doc_engine(k, sched, 12, step_every=11, **kw)
        _assert_identical(ref, eng)


# --------------------------------------------------------- tree K-invariance

def _run_tree_engine(megastep_k, svc, n_docs, step_every=9, **kw):
    kw.setdefault("capacity", 512)
    kw.setdefault("pool_capacity", 2048)
    eng = TreeBatchEngine(
        n_docs, ops_per_step=4, megastep_k=megastep_k, **kw,
    )
    i = 0
    for d in range(n_docs):
        for msg in svc.document(f"doc{d}").sequencer.log:
            eng.ingest(d, msg)
            i += 1
            if i % step_every == 0:
                eng.step()
    eng.step()
    return eng


def _assert_tree_identical(a: TreeBatchEngine, b: TreeBatchEngine) -> None:
    for x, y in zip(jax.tree.leaves(a.state), jax.tree.leaves(b.state)):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes()
    assert sorted(a.fallbacks) == sorted(b.fallbacks)
    for d in range(a.n_docs):
        assert a.tree_json(d) == b.tree_json(d), f"doc {d}"


@pytest.mark.parametrize("nested_prob", [0.0, 1.0])
def test_k_invariance_tree_engine(nested_prob):
    """Tree-family K-invariance, with (nested_prob=1.0) and without
    nested-field edits in the mix (both ride the columnar device path)."""
    from test_tree_batch_engine import drive_tree_docs

    svc, expected = drive_tree_docs(4, seed=7, steps=24, nested_prob=nested_prob)
    a = _run_tree_engine(1, svc, 4)
    b = _run_tree_engine(8, svc, 4)
    assert b.health()["steps_per_dispatch"] > 1.0
    _assert_tree_identical(a, b)
    for d in range(4):
        assert a.values(d) == b.values(d) == expected[d]


def test_k_invariance_tree_fallback_routing():
    """A wide leaf (wider than one payload row) routes its doc to the host
    fallback at the same megastep-granularity observation point for K=1
    and K=8, while a sibling doc stays columnar — membership, values, and
    device state all identical."""
    from test_tree_batch_engine import drive_tree_docs
    from fluidframework_tpu.dds.channels import default_registry
    from fluidframework_tpu.dds.tree.changeset import make_insert
    from fluidframework_tpu.dds.tree.schema import leaf
    from fluidframework_tpu.runtime import ContainerRuntime

    svc, expected = drive_tree_docs(2, seed=11, steps=16)
    doc = svc.document("doc0")
    rt = ContainerRuntime(default_registry(), container_id="wide")
    rt.create_datastore("root").create_channel("sharedTree", "t")
    rt.connect(doc, "wide")
    doc.process_all()
    t = rt.datastore("root").get_channel("t")
    t.submit_change(make_insert([], "", 0, [leaf("x" * 100)]))
    t.submit_change(make_insert([], "", 1, [leaf(7)]))
    rt.flush()
    doc.process_all()
    a = _run_tree_engine(1, svc, 2)
    b = _run_tree_engine(8, svc, 2)
    assert 0 in b.fallbacks, "wide leaf must route doc 0 to fallback"
    assert 1 not in b.fallbacks
    _assert_tree_identical(a, b)
    assert a.values(1) == b.values(1) == expected[1]


# ----------------------------------------------------------------- counters

def test_megastep_counters_in_health_and_fleet_status():
    """CI smoke (ISSUE 4 satellite): the megastep pipeline surfaces
    ``steps_per_dispatch`` / ``megastep_k`` / ``staging_overlap_packs``
    through engine health AND the fleet status line, and a deep queue
    actually amortizes dispatches (steps_per_dispatch > 1)."""
    sched = _schedule(6, 16, seed=5)
    # megastep_k=2 reuses the K=2 program the invariance tests compiled.
    eng = _run_doc_engine(2, sched, 6, step_every=10**9)  # one deep drain
    h = eng.health()
    assert h["megastep_k"] == 2
    assert h["steps_per_dispatch"] > 1.0
    assert h["megastep_slices"] > h["megastep_dispatches"] >= 1
    assert "staging_overlap_packs" in h
    status = status_snapshot(eng, [str(d) for d in range(6)], rows=7)
    assert status["rows"] == 7
    for key in ("steps_per_dispatch", "megastep_k", "staging_overlap_packs"):
        assert key in status["health"], key
    # K=1 reports the degenerate ratio (1.0) — the exact legacy path.
    legacy = _run_doc_engine(1, _schedule(6, 4, seed=6), 6)
    assert legacy.health()["steps_per_dispatch"] == 1.0
    # Tree engine surfaces the same counter family.
    th = TreeBatchEngine(2, megastep_k=4).health()
    assert th["megastep_k"] == 4 and "steps_per_dispatch" in th
    assert "staging_aliased_swaps" in h and "staging_aliased_swaps" in th
