"""Merge-tree oracle: unit semantics + multi-client convergence fuzz.

Mirrors the reference's merge-tree test strategy (SURVEY.md §4): directed
unit tests for tie-break/visibility edge cases plus randomized "farm" rounds
where N clients edit concurrently through the sequencer and must converge.
"""

import random

import pytest

from fluidframework_tpu.dds.mergetree_ref import RefMergeTree
from fluidframework_tpu.dds.shared_string import SharedString
from fluidframework_tpu.protocol.stamps import ALL_ACKED, LOCAL_BASE
from fluidframework_tpu.server.local_service import LocalDocument


def make_clients(doc: LocalDocument, n: int) -> list[SharedString]:
    clients = []
    for i in range(n):
        c = SharedString(client_id=f"c{i}")
        doc.connect(c.client_id, c.process)
        clients.append(c)
    doc.process_all()  # deliver joins so short ids are assigned
    return clients


def pump(doc: LocalDocument, clients: list[SharedString]) -> None:
    """Flush every outbox through the sequencer and deliver everything."""
    moved = True
    while moved:
        moved = False
        for c in clients:
            for m in c.take_outbox():
                doc.submit(m)
                moved = True
        if doc.pending_count:
            doc.process_all()
            moved = True


class TestDirectedSemantics:
    def test_single_client_insert_remove(self):
        doc = LocalDocument("d")
        (a,) = make_clients(doc, 1)
        a.insert_text(0, "hello world")
        a.remove_range(5, 11)
        a.insert_text(5, "!")
        pump(doc, [a])
        assert a.text == "hello!"

    def test_concurrent_inserts_same_position_later_seq_wins_front(self):
        """Two clients insert at pos 0 concurrently: the op sequenced LATER
        lands closer to the front (reference breakTie: incoming stamp greater
        than the concurrent segment's stamp goes before it)."""
        doc = LocalDocument("d")
        a, b = make_clients(doc, 2)
        a.insert_text(0, "A")
        b.insert_text(0, "B")
        # a's op is submitted first -> seq smaller; b's op sequenced later.
        pump(doc, [a, b])
        assert a.text == b.text == "BA"

    def test_local_pending_stays_in_front_of_remote_insert(self):
        """Reference: local unacked stamps outrank all acked stamps, so a
        remote insert at the same position does not jump a pending local
        segment."""
        doc = LocalDocument("d")
        a, b = make_clients(doc, 2)
        b.insert_text(0, "B")
        for m in b.take_outbox():
            doc.submit(m)
        a.insert_text(0, "A")  # pending on a
        doc.process_all()  # delivers b's op to a while a's op still pending
        # On a: local pending "A" outranks the acked remote "B".
        assert a.text == "AB"
        pump(doc, [a, b])
        # After a's op is sequenced (later than b's), both converge to "AB".
        assert a.text == b.text == "AB"

    def test_insert_goes_before_tombstone(self):
        """Inserting at a boundary adjacent to removed text lands before the
        tombstone (breakTie: incoming acked stamp > old insert stamp)."""
        doc = LocalDocument("d")
        a, b = make_clients(doc, 2)
        a.insert_text(0, "ab")
        pump(doc, [a, b])
        a.remove_range(1, 2)  # remove 'b'
        pump(doc, [a, b])
        b.insert_text(1, "X")  # at end of visible text, before tombstone 'b'
        pump(doc, [a, b])
        assert a.text == b.text == "aX"
        # The tombstone is evicted once MSN passes the remove.
        backend = a.backend
        assert isinstance(backend, RefMergeTree)

    def test_concurrent_remove_overlap_converges(self):
        doc = LocalDocument("d")
        a, b = make_clients(doc, 2)
        a.insert_text(0, "abcdef")
        pump(doc, [a, b])
        a.remove_range(1, 4)
        b.remove_range(2, 6)
        pump(doc, [a, b])
        assert a.text == b.text == "a"

    def test_remove_does_not_affect_concurrent_insert(self):
        """Set-remove only removes what was visible in the op's perspective:
        a concurrent insert inside the removed range survives."""
        doc = LocalDocument("d")
        a, b = make_clients(doc, 2)
        a.insert_text(0, "abcd")
        pump(doc, [a, b])
        a.remove_range(0, 4)
        b.insert_text(2, "X")
        pump(doc, [a, b])
        assert a.text == b.text == "X"

    def test_annotate_lww_converges(self):
        doc = LocalDocument("d")
        a, b = make_clients(doc, 2)
        a.insert_text(0, "abcd")
        pump(doc, [a, b])
        a.annotate_range(0, 3, 7, 100)
        b.annotate_range(1, 4, 7, 200)
        pump(doc, [a, b])
        ann_a = a.backend.annotations(ALL_ACKED, a.short_client)
        ann_b = b.backend.annotations(ALL_ACKED, b.short_client)
        assert ann_a == ann_b
        # b's annotate sequenced later -> wins on the overlap [1,3).
        assert ann_a == [{7: 100}, {7: 200}, {7: 200}, {7: 200}]

    def test_zamboni_eviction_preserves_text(self):
        doc = LocalDocument("d")
        a, b = make_clients(doc, 2)
        a.insert_text(0, "abcdef")
        pump(doc, [a, b])
        a.remove_range(1, 3)
        pump(doc, [a, b])
        # Force MSN to advance by having both clients op afterwards.
        a.insert_text(0, "x")
        pump(doc, [a, b])
        b.insert_text(0, "y")
        pump(doc, [a, b])
        assert a.text == b.text
        # Tombstones below MSN are gone on both replicas.
        for client in (a, b):
            backend = client.backend
            assert all(len(s.text) > 0 for s in backend.segments)


OPS = (
    "insert", "insert", "insert", "remove", "annotate",
    "obliterate", "obliterate_sided",
)


def draw_op(rng: random.Random, n: int, alphabet: str = "abcdefgh") -> tuple:
    """Draw one random op descriptor against a document of visible length n.

    Pure rng consumption — separated from application so the shrinker in
    _debug_farm.py can keep rng schedules aligned while skipping issuance.
    """
    kind = rng.choice(OPS)
    if kind == "insert" or n == 0:
        pos = rng.randint(0, n)
        text = "".join(rng.choice(alphabet) for _ in range(rng.randint(1, 4)))
        return ("insert", pos, text)
    p1 = rng.randint(0, n - 1)
    p2 = rng.randint(p1 + 1, n)
    if kind == "remove":
        return ("remove", p1, p2)
    if kind == "obliterate":
        return ("obliterate", p1, p2)
    if kind == "obliterate_sided":
        # Sided endpoint CHARACTERS c1 <= c2 with sides such that the range
        # boundary is non-inverted (start_bound <= end_bound).
        c1 = rng.randint(0, n - 1)
        c2 = rng.randint(c1, n - 1)
        s1 = rng.random() < 0.5  # before?
        s2 = rng.random() < 0.5
        if c1 == c2 and not s1 and s2:
            s1 = True  # (c,After)..(c,Before) would invert; degrade
        return ("obliterate_sided", (c1, s1), (c2, s2))
    return ("annotate", p1, p2, rng.randint(0, 3), rng.randint(0, 1000))


def issue_op(c: SharedString, op: tuple) -> None:
    if op[0] == "insert":
        c.insert_text(op[1], op[2])
    elif op[0] == "remove":
        c.remove_range(op[1], op[2])
    elif op[0] == "obliterate":
        c.obliterate_range(op[1], op[2])
    elif op[0] == "obliterate_sided":
        c.obliterate_range_sided(op[1], op[2])
    else:
        c.annotate_range(op[1], op[2], op[3], op[4])


def random_op(rng: random.Random, c: SharedString, alphabet: str) -> None:
    issue_op(c, draw_op(rng, len(c.text), alphabet))


def canon_annotations(replica) -> tuple:
    """Order-insensitive canonical form of a replica's annotations (dict
    iteration order differs between backends; content must not)."""
    return tuple(
        tuple(sorted(d.items()))
        for d in replica.backend.annotations(ALL_ACKED, replica.short_client)
    )


@pytest.mark.parametrize("seed", range(25))
def test_conflict_farm_convergence(seed):
    """N clients make interleaved concurrent edits with randomized delivery;
    all replicas (and a pure-observer replica) must converge exactly.

    Reference analog: merge-tree client.conflictFarm.spec.ts.
    """
    rng = random.Random(seed)
    doc = LocalDocument("d")
    n_clients = rng.randint(2, 4)
    clients = make_clients(doc, n_clients)
    observer = SharedString(client_id="observer")  # never edits
    doc.connect(observer.client_id, observer.process)
    doc.process_all()

    for _round in range(rng.randint(5, 15)):
        for c in clients:
            for _ in range(rng.randint(0, 3)):
                random_op(rng, c, "abcdefgh")
            # Randomly flush some outboxes early (partial interleaving).
            if rng.random() < 0.7:
                for m in c.take_outbox():
                    doc.submit(m)
        # Deliver a random prefix of the sequenced stream.
        doc.process_some(rng.randint(0, doc.pending_count))

    pump(doc, clients + [observer])
    texts = {c.text for c in clients}
    assert len(texts) == 1, f"divergent texts: {texts}"
    assert observer.backend.visible_text(ALL_ACKED, observer.short_client) == clients[0].text
    anns = {canon_annotations(c) for c in clients + [observer]}
    assert len(anns) == 1, "divergent annotations"
