"""Small DDS family tests: cell, counter, consensus queue, register
collection, task manager, pact map — multi-client convergence through the
full runtime over the in-process service (SURVEY.md §4.1 pattern)."""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.dds.small import SMALL_DDS_FACTORIES
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService


def registry():
    r = default_registry()
    r.update(SMALL_DDS_FACTORIES)
    return r


CHANNELS = [
    ("sharedCell", "cell"),
    ("sharedCounter", "counter"),
    ("consensusQueue", "queue"),
    ("consensusRegisterCollection", "regs"),
    ("taskManager", "tasks"),
    ("pactMap", "pact"),
]


def mk(doc, name, stash=None):
    c = ContainerRuntime(registry(), container_id=name)
    ds = c.create_datastore("root")
    for ctype, cid in CHANNELS:
        ds.create_channel(ctype, cid)
    c.connect(doc, name, stash=stash)
    return c


def ch(c, cid):
    return c.datastore("root").get_channel(cid)


@pytest.fixture
def pair():
    svc = LocalService()
    doc = svc.document("d")
    a, b = mk(doc, "A"), mk(doc, "B")
    doc.process_all()
    return doc, a, b


# ------------------------------------------------------------------- cell

def test_cell_lww_and_overlay(pair):
    doc, a, b = pair
    ch(a, "cell").set({"v": 1})
    assert ch(a, "cell").get() == {"v": 1}  # optimistic
    assert ch(b, "cell").get() is None
    a.flush()
    ch(b, "cell").set({"v": 2})
    b.flush()
    doc.process_all()
    # B's set sequenced after A's: LWW winner everywhere.
    assert ch(a, "cell").get() == ch(b, "cell").get() == {"v": 2}
    ch(a, "cell").delete()
    a.flush()
    doc.process_all()
    assert ch(b, "cell").empty and ch(a, "cell").get() is None


# ---------------------------------------------------------------- counter

def test_counter_commutes(pair):
    doc, a, b = pair
    ch(a, "counter").increment(5)
    ch(b, "counter").increment(-2)
    assert ch(a, "counter").value == 5  # local overlay
    assert ch(b, "counter").value == -2
    a.flush(); b.flush()
    doc.process_all()
    assert ch(a, "counter").value == ch(b, "counter").value == 3
    ch(a, "counter").increment(10)
    assert ch(a, "counter").value == 13
    a.flush(); doc.process_all()
    assert ch(b, "counter").value == 13
    with pytest.raises(TypeError):
        ch(a, "counter").increment(1.5)


# ------------------------------------------------------------------ queue

def test_consensus_queue_acquire_complete(pair):
    doc, a, b = pair
    ch(a, "queue").add("job1")
    ch(a, "queue").add("job2")
    a.flush()
    ha = ch(a, "queue").acquire()
    a.flush()
    hb = ch(b, "queue").acquire()
    b.flush()
    assert not ha.settled  # consensus: nothing until sequenced
    doc.process_all()
    assert ha.settled and ha.acquired and ha.value == "job1"
    assert hb.settled and hb.acquired and hb.value == "job2"
    assert ch(a, "queue").data == ch(b, "queue").data == []
    ch(a, "queue").complete(ha)
    ch(b, "queue").release(hb)
    a.flush(); b.flush()
    doc.process_all()
    # job1 completed; job2 released back.
    assert ch(a, "queue").data == ch(b, "queue").data == ["job2"]


def test_consensus_queue_releases_on_leave(pair):
    doc, a, b = pair
    ch(a, "queue").add("x")
    a.flush(); doc.process_all()
    hb = ch(b, "queue").acquire()
    b.flush(); doc.process_all()
    assert hb.acquired
    b.disconnect()
    doc.process_all()
    # B left holding "x": it returns to the queue on A's replica.
    assert ch(a, "queue").data == ["x"]


def test_acquire_on_empty_queue_settles_unacquired(pair):
    doc, a, b = pair
    h = ch(a, "queue").acquire()
    a.flush(); doc.process_all()
    assert h.settled and not h.acquired


# -------------------------------------------------------------- registers

def test_register_concurrent_versions(pair):
    doc, a, b = pair
    wa = ch(a, "regs").write("k", "from-a")
    wb = ch(b, "regs").write("k", "from-b")
    a.flush(); b.flush()
    doc.process_all()
    # Concurrent writes: A sequenced first and wins atomic; both versions kept.
    assert ch(a, "regs").read("k") == ch(b, "regs").read("k") == "from-a"
    assert ch(a, "regs").read("k", "lww") == "from-b"
    assert set(ch(b, "regs").read_versions("k")) == {"from-a", "from-b"}
    assert ch(a, "regs").write_result(wa) is True
    assert ch(b, "regs").write_result(wb) is False

    # A non-concurrent later write supersedes all versions.
    wc = ch(b, "regs").write("k", "final")
    b.flush(); doc.process_all()
    assert ch(a, "regs").read_versions("k") == ["final"]
    assert ch(a, "regs").read("k") == "final"
    assert ch(b, "regs").write_result(wc) is True


# ------------------------------------------------------------ task manager

def test_task_manager_election_and_leave(pair):
    doc, a, b = pair
    ch(a, "tasks").volunteer("t")
    ch(b, "tasks").volunteer("t")
    a.flush(); b.flush()
    doc.process_all()
    assert ch(a, "tasks").assignee("t") == "A"
    assert ch(a, "tasks").assigned("t") and not ch(b, "tasks").assigned("t")
    assert ch(b, "tasks").queued("t")

    a.disconnect()  # assignee leaves -> lock passes to B
    doc.process_all()
    assert ch(b, "tasks").assigned("t")

    ch(b, "tasks").complete("t")
    b.flush(); doc.process_all()
    assert ch(b, "tasks").assignee("t") is None


def test_task_manager_abandon(pair):
    doc, a, b = pair
    ch(a, "tasks").volunteer("t")
    a.flush(); doc.process_all()
    ch(a, "tasks").abandon("t")
    a.flush(); doc.process_all()
    assert ch(b, "tasks").assignee("t") is None


# --------------------------------------------------------------- pact map

def test_pact_map_requires_all_signoffs(pair):
    doc, a, b = pair
    ch(a, "pact").set("policy", "strict")
    a.flush()
    doc.process_all()  # set sequences; A and B auto-submit accepts...
    a.flush(); b.flush()  # ...which ride the next flush
    doc.process_all()
    assert ch(a, "pact").get("policy") == ch(b, "pact").get("policy") == "strict"
    assert not ch(a, "pact").is_pending("policy")


def test_pact_map_pending_until_signoff(pair):
    doc, a, b = pair
    ch(a, "pact").set("k", 1)
    a.flush()
    doc.process_all()
    a.flush()  # A's accept goes out
    # Deliver only A's accept: B's accept (auto-flushed on inbound per the
    # ref-seq consistency rule) stays queued at the service.
    doc.process_some(1)
    assert ch(a, "pact").get("k") is None
    assert ch(a, "pact").is_pending("k")
    assert ch(a, "pact").get_pending("k") == 1
    doc.process_all()  # B's accept lands
    assert ch(b, "pact").get("k") == 1
    assert ch(a, "pact").get("k") == 1


def test_pact_map_leave_counts_as_signoff(pair):
    doc, a, b = pair
    ch(a, "pact").set("k", "v")
    a.flush()
    doc.process_all()
    b.disconnect()  # B leaves before its accept ever goes out
    a.flush()  # A accepts
    doc.process_all()  # A's accept + B's leave -> implicit signoff
    assert ch(a, "pact").get("k") == "v"


def test_pact_map_rejects_stale_proposal(pair):
    doc, a, b = pair
    ch(a, "pact").set("k", "first")
    a.flush(); doc.process_all()
    a.flush(); b.flush(); doc.process_all()  # accepted
    assert ch(b, "pact").get("k") == "first"

    # B proposes concurrently-with-acceptance... a second set while nothing
    # is pending and with knowledge of accepted value: valid.
    ch(b, "pact").set("k", "second")
    b.flush(); doc.process_all()
    a.flush(); b.flush(); doc.process_all()
    assert ch(a, "pact").get("k") == ch(b, "pact").get("k") == "second"


# ---------------------------------------------------------- reconnect/stash

def test_small_dds_reconnect_replay(pair):
    doc, a, b = pair
    ch(a, "counter").increment(7)
    ch(a, "cell").set("offline")
    a.disconnect()
    a.flush()
    a.connect(doc, "A2")
    doc.process_all()
    assert ch(b, "counter").value == 7
    assert ch(b, "cell").get() == "offline"
    assert ch(a, "counter").value == 7


def test_small_dds_summary_roundtrip(pair):
    doc, a, b = pair
    ch(a, "cell").set(42)
    ch(a, "counter").increment(9)
    ch(a, "regs").write("r", "v")
    ch(a, "tasks").volunteer("t")
    a.flush(); doc.process_all()

    summary = a.datastore("root").summarize()
    c = ContainerRuntime(registry(), container_id="C")
    ds = c.create_datastore("root")
    ds.load(summary)
    assert ds.get_channel("cell").get() == 42
    assert ds.get_channel("counter").value == 9
    assert ds.get_channel("regs").read("r") == "v"
    assert ds.get_channel("tasks").assignee("t") == "A"
