"""Per-segment attribution (VERDICT r3 missing #4).

The columnar insert stamps (ins_key/ins_client) ARE the attribution data —
these tests pin the query surface (ref attributionCollection.ts getAtOffset
:203 / getKeysInOffsetRange:213), the snapshotV1 attribution channel
(serializer :465, populate :389 — who-wrote-what survives below-MSN
coalescing), resolution through the interned OpStreamAttributor
(framework/attributor), and oracle/kernel agreement under randomized
concurrent editing.
"""

from __future__ import annotations

import random

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.dds.mergetree_ref import RefMergeTree
from fluidframework_tpu.dds.snapshot_v1 import (
    decode_snapshot_v1,
    encode_snapshot_v1,
)
from fluidframework_tpu.framework.attributor import OpStreamAttributor
from fluidframework_tpu.protocol.stamps import ALL_ACKED
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService


def make_container(doc, name: str) -> ContainerRuntime:
    c = ContainerRuntime(default_registry(), container_id=name)
    ds = c.create_datastore("root")
    ds.create_channel("sharedString", "text")
    c.connect(doc, name)
    return c


def string_of(c: ContainerRuntime):
    return c.datastore("root").get_channel("text")


def settle(doc, *containers):
    for c in containers:
        c.flush()
    doc.process_all()


def test_attribution_tracks_writers():
    """Each visible char attributes to the sequenced op that inserted it,
    across concurrent writers, identically on every replica."""
    svc = LocalService()
    doc = svc.document("d")
    a, b = make_container(doc, "A"), make_container(doc, "B")
    doc.process_all()

    string_of(a).insert_text(0, "aaaa")
    settle(doc, a, b)
    string_of(b).insert_text(2, "BB")
    settle(doc, a, b)
    assert string_of(a).text == "aaBBaa"

    # The attributor consumes the sequenced stream (ref OpStreamAttributor
    # listens on op events, attributor.ts:87).
    attributor = OpStreamAttributor()
    for msg in doc.ops_range(1, 1 << 20):
        attributor.observe(msg)

    for c in (a, b):
        ch = string_of(c)
        keys = [ch.attribution_at(i) for i in range(6)]
        assert [k["type"] for k in keys] == ["op"] * 6
        # One seq wrote the a-run, a later seq wrote the B-run.
        assert keys[0] == keys[1] == keys[4] == keys[5]
        assert keys[2] == keys[3]
        assert keys[2]["seq"] > keys[0]["seq"]
        # Resolution through the op-stream table names the actual writers.
        assert attributor.get(keys[0]["seq"])["client"] == "A"
        assert attributor.get(keys[2]["seq"])["client"] == "B"

    # Range query: runs covering [1, 5) — first offset may precede start
    # (ref getKeysInOffsetRange:213).
    runs = string_of(a).attribution_range(1, 5)
    assert [r["offset"] for r in runs] == [0, 2, 4]


def test_pending_local_content_attributes_as_local():
    svc = LocalService()
    doc = svc.document("d")
    a = make_container(doc, "A")
    doc.process_all()
    string_of(a).insert_text(0, "x")  # not flushed: pending
    assert string_of(a).attribution_at(0) == {"type": "local"}
    settle(doc, a)
    assert string_of(a).attribution_at(0)["type"] == "op"


def test_snapshot_v1_attribution_survives_coalescing():
    """Below-MSN segments coalesce in the V1 snapshot (stamps dropped), but
    the attribution channel preserves exact per-char provenance."""
    tree = RefMergeTree()
    # Three writers' acked inserts, all below the MSN.
    tree.apply_insert(0, "aaaa", 1, 0, 0)
    tree.apply_insert(4, "bb", 2, 1, 1)
    tree.apply_insert(6, "cc", 3, 2, 2)
    tree.update_min_seq(3)

    names = ["w0", "w1", "w2"]
    blobs = encode_snapshot_v1(
        tree, seq=3, get_long_client_id=lambda s: names[s], attribution=True
    )
    # The coalesced snapshot melts everything into one spec...
    import json

    header = json.loads(blobs["header"])
    assert header["segments"] == ["aaaabbcc"]
    # ...but the attribution channel keeps the three runs, reference-shaped.
    assert header["attribution"] == {
        "seqs": [1, 2, 3],
        "posBreakpoints": [0, 4, 6],
        "length": 8,
    }

    loaded, _seq, _min = decode_snapshot_v1(blobs, names.index)
    assert loaded.visible_text(ALL_ACKED, -1) == "aaaabbcc"
    assert loaded.attribution_runs(ALL_ACKED, -1) == [(0, 1), (4, 2), (6, 3)]
    assert loaded.attribution_at(5, ALL_ACKED, -1) == 2

    # Second-generation snapshot: overrides re-serialize losslessly.
    blobs2 = encode_snapshot_v1(
        loaded, seq=3, get_long_client_id=lambda s: names[s], attribution=True
    )
    assert json.loads(blobs2["header"])["attribution"] == header["attribution"]


def test_snapshot_v1_attribution_spans_mixed_segments():
    """Attribution runs span coalesced AND merge-info segments, split
    correctly across chunk boundaries."""
    tree = RefMergeTree()
    tree.apply_insert(0, "old", 1, 0, 0)     # below MSN after advance
    tree.apply_insert(3, "newer", 5, 1, 4)   # above MSN: keeps merge info
    tree.update_min_seq(4)
    blobs = encode_snapshot_v1(
        tree, seq=5, get_long_client_id=lambda s: f"w{s}",
        chunk_size=3, attribution=True,  # force the 2nd seg into a body chunk
    )
    import json

    header = json.loads(blobs["header"])
    assert header["segments"] == ["old"]
    assert header["attribution"] == {
        "seqs": [1], "posBreakpoints": [0], "length": 3,
    }
    body = json.loads(blobs["body_0"])
    assert body["segments"][0]["seq"] == 5
    assert body["attribution"] == {
        "seqs": [5], "posBreakpoints": [0], "length": 5,
    }
    loaded, _s, _m = decode_snapshot_v1(blobs, lambda n: int(n[1:]))
    assert loaded.attribution_runs(ALL_ACKED, -1) == [(0, 1), (3, 5)]


def test_attributor_summary_roundtrip_resolves_keys():
    att = OpStreamAttributor()
    for seq, client in [(1, "alice"), (2, "bob"), (3, "alice")]:
        att.record(seq, client, 1000.0 + seq)
    summary = att.summarize()
    assert summary["clients"] == ["alice", "bob"]  # interned once each
    restored = OpStreamAttributor()
    restored.load(summary)
    assert restored.get(3) == {"client": "alice", "timestamp": 1003.0}


@pytest.mark.device
def test_attribution_oracle_kernel_agreement_under_fuzz():
    """Randomized concurrent editing on a mixed oracle/kernel fleet: every
    replica reports identical attribution runs."""
    import itertools

    from fluidframework_tpu.dds import channels as ch_mod
    from fluidframework_tpu.dds.kernel_backend import KernelMergeTree

    counter = itertools.count()

    def factory():
        if next(counter) % 2 == 0:
            return KernelMergeTree(
                max_segments=1024, remove_slots=6, text_capacity=16384,
                max_insert_len=8, ob_slots=16,
            )
        return RefMergeTree()

    ch_mod.set_string_backend_factory(factory)
    try:
        rng = random.Random(11)
        svc = LocalService()
        doc = svc.document("d")
        conts = [make_container(doc, f"C{i}") for i in range(3)]
        doc.process_all()
        for _round in range(6):
            for c in conts:
                ch = c.datastore("root").get_channel("text")
                n = len(ch.text)
                op = rng.random()
                if op < 0.6 or n < 4:
                    ch.insert_text(
                        rng.randint(0, n),
                        "".join(rng.choice("xyz") for _ in range(rng.randint(1, 4))),
                    )
                elif op < 0.85:
                    p = rng.randint(0, n - 2)
                    ch.remove_range(p, p + rng.randint(1, min(3, n - p)))
                else:
                    p = rng.randint(0, n - 2)
                    ch.obliterate_range(p, p + 1)
            settle(doc, *conts)
        texts = {string_of(c).text for c in conts}
        assert len(texts) == 1
        runs = {
            tuple(
                (r["offset"], r["key"]["seq"])
                for r in string_of(c).attribution_range()
            )
            for c in conts
        }
        assert len(runs) == 1, f"attribution divergence: {runs}"
    finally:
        ch_mod.set_string_backend_factory(None)


def test_runtime_attributor_rides_summary_cycle():
    """The container-level attributor (ref mixinAttributor): sequenced ops
    record {client, timestamp}; the table rides summaries interned, late
    joiners restore it and resolve per-segment attribution keys to users
    — without opting in themselves."""
    from fluidframework_tpu.driver import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Container
    from fluidframework_tpu.runtime.summary import SummaryConfig

    svc = LocalService()
    factory = LocalDocumentServiceFactory(svc)
    d = Container.create_detached(
        default_registry(), container_id="alice", track_attribution=True
    )
    d.runtime.create_datastore("root").create_channel("sharedString", "text")
    d.attach("doc", factory, "alice")
    svc.process_all()
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))

    ch = d.runtime.datastore("root").get_channel("text")
    ch.insert_text(0, "hers")
    d.runtime.flush()
    svc.process_all()
    b = Container.load("doc", factory, default_registry(), "bob")
    svc.process_all()
    bch = b.runtime.datastore("root").get_channel("text")
    bch.insert_text(0, "his-")
    b.runtime.flush()
    svc.process_all()
    assert sm.tick(now=0.0)
    svc.process_all()
    assert sm.acked == 1

    late = Container.load("doc", factory, default_registry(), "carol")
    svc.process_all()
    assert late.runtime.attributor is not None  # restored from the snapshot
    lch = late.runtime.datastore("root").get_channel("text")
    assert lch.text == "his-hers"
    who = lambda pos: late.runtime.attributor.get(
        lch.attribution_at(pos)["seq"]
    )["client"]
    assert who(0) == "bob" and who(4) == "alice"
