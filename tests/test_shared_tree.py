"""SharedTree end-to-end tests: multi-client convergence over the full
runtime stack, EditManager trunk/peer-branch behavior, reconnect/stash,
rollback, summaries, schema ops, and a randomized convergence farm.

Mirrors the reference's SharedTree suites (tree/src/test/shared-tree/) and
the EditManager bench/peer scenarios (shared-tree-core/edit-manager/)."""

from __future__ import annotations

import random

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.dds.tree import EditManager, Forest, NodeChange
from fluidframework_tpu.dds.tree.changeset import (
    apply_node_change,
    clone_change,
    make_insert,
    make_remove,
    make_set_value,
)
from fluidframework_tpu.dds.tree.schema import (
    FieldKind,
    FieldSchema,
    SchemaRegistry,
    array_schema,
    build_node,
    leaf,
)
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService


def make_container(doc, name: str, stash: str | None = None) -> ContainerRuntime:
    c = ContainerRuntime(default_registry(), container_id=name)
    ds = c.create_datastore("root")
    ds.create_channel("sharedTree", "tree")
    c.connect(doc, name, stash=stash)
    return c


def tree_of(c: ContainerRuntime):
    return c.datastore("root").get_channel("tree")


def root_values(c: ContainerRuntime) -> list:
    return [n.value for n in tree_of(c).forest.root_field]


def setup_pair():
    svc = LocalService()
    doc = svc.document("d1")
    a = make_container(doc, "A")
    b = make_container(doc, "B")
    doc.process_all()
    return svc, doc, a, b


# --------------------------------------------------------------------------
# basic convergence
# --------------------------------------------------------------------------

def test_two_client_concurrent_inserts_converge():
    svc, doc, a, b = setup_pair()
    tree_of(a).submit_change(make_insert([], "", 0, [leaf(1), leaf(2)]))
    a.flush()
    tree_of(b).submit_change(make_insert([], "", 0, [leaf(10)]))
    b.flush()
    doc.process_all()
    assert root_values(a) == root_values(b)
    # A flushed first -> sequenced first -> its content stays left.
    assert root_values(a) == [1, 2, 10]


def test_concurrent_remove_and_set_value():
    svc, doc, a, b = setup_pair()
    tree_of(a).submit_change(make_insert([], "", 0, [leaf(i) for i in range(5)]))
    a.flush()
    doc.process_all()
    tree_of(a).submit_change(make_remove([], "", 1, 2))
    a.flush()
    tree_of(b).submit_change(make_set_value([("", 1)], 99))  # node removed by A
    tree_of(b).submit_change(make_set_value([("", 4)], 44))  # survives
    b.flush()
    doc.process_all()
    assert root_values(a) == root_values(b) == [0, 3, 44]


def test_nested_object_edits_converge():
    svc, doc, a, b = setup_pair()
    root = build_node("doc", items=[leaf(1)], title=leaf("t"))
    tree_of(a).submit_change(make_insert([], "", 0, [root]))
    a.flush()
    doc.process_all()
    tree_of(a).submit_change(make_insert([("", 0)], "items", 1, [leaf(2)]))
    a.flush()
    tree_of(b).submit_change(make_set_value([("", 0), ("title", 0)], "both"))
    b.flush()
    doc.process_all()
    fa, fb = tree_of(a).forest, tree_of(b).forest
    assert fa.equal(fb)
    node = fa.root_field[0]
    assert [n.value for n in node.fields["items"]] == [1, 2]
    assert node.fields["title"][0].value == "both"


def test_own_pending_overlay_and_ack():
    svc, doc, a, b = setup_pair()
    tree_of(a).submit_change(make_insert([], "", 0, [leaf(7)]))
    # Optimistic local view before sequencing:
    assert root_values(a) == [7]
    assert root_values(b) == []
    a.flush()
    doc.process_all()
    assert root_values(a) == root_values(b) == [7]
    assert not tree_of(a)._local_pending


def test_interleaved_rounds_three_clients():
    svc = LocalService()
    doc = svc.document("d1")
    cs = [make_container(doc, n) for n in ("A", "B", "C")]
    doc.process_all()
    for rnd in range(6):
        for i, c in enumerate(cs):
            vals = root_values(c)
            tree_of(c).submit_change(
                make_insert([], "", len(vals), [leaf(rnd * 10 + i)])
            )
            c.flush()
        doc.process_all()
    assert root_values(cs[0]) == root_values(cs[1]) == root_values(cs[2])
    assert len(root_values(cs[0])) == 18


# --------------------------------------------------------------------------
# reconnect / stash / rollback
# --------------------------------------------------------------------------

def test_reconnect_resubmits_rebased_edits():
    svc, doc, a, b = setup_pair()
    tree_of(a).submit_change(make_insert([], "", 0, [leaf(0), leaf(1)]))
    a.flush()
    doc.process_all()
    a.disconnect()
    tree_of(a).submit_change(make_insert([], "", 2, [leaf(2)]))  # offline edit
    tree_of(b).submit_change(make_insert([], "", 0, [leaf(-1)]))  # concurrent
    b.flush()
    doc.process_all()
    a.connect(doc, "A2")
    doc.process_all()
    assert root_values(a) == root_values(b) == [-1, 0, 1, 2]


def test_stash_and_rehydrate():
    svc, doc, a, b = setup_pair()
    tree_of(a).submit_change(make_insert([], "", 0, [leaf(0)]))
    a.flush()
    doc.process_all()
    a.disconnect()
    tree_of(a).submit_change(make_insert([], "", 1, [leaf(1)]))
    stash = a.get_pending_local_state()
    a.close()

    c = ContainerRuntime(default_registry(), container_id="A2")
    ds = c.create_datastore("root")
    ds.create_channel("sharedTree", "tree")
    c.connect(doc, "A2", stash=stash)
    doc.process_all()
    assert root_values(c) == root_values(b) == [0, 1]


def test_rollback_staged_edits():
    svc, doc, a, b = setup_pair()
    tree_of(a).submit_change(make_insert([], "", 0, [leaf(0)]))
    a.flush()
    doc.process_all()
    tree_of(a).submit_change(make_insert([], "", 1, [leaf(1)]))
    tree_of(a).submit_change(make_set_value([("", 0)], 100))
    assert root_values(a) == [100, 1]
    a.rollback_staged()
    assert root_values(a) == [0]
    doc.process_all()
    assert root_values(a) == root_values(b) == [0]


# --------------------------------------------------------------------------
# summaries / schema
# --------------------------------------------------------------------------

def test_channel_summary_roundtrip_with_peer_branches():
    svc, doc, a, b = setup_pair()
    tree_of(a).submit_change(
        make_insert([], "", 0, [build_node("pt", x=i, y=2 * i) for i in range(8)])
    )
    a.flush()
    doc.process_all()
    summary = tree_of(a).summarize()
    # uniform run of pt nodes columnarizes in the summary
    assert any("chunk" in e for e in summary["forest"])

    from fluidframework_tpu.dds.tree import SharedTreeChannel

    fresh = SharedTreeChannel("tree")
    fresh.load(summary)
    assert fresh.forest.equal(tree_of(a).forest)
    assert fresh.em.summarize() == tree_of(a).em.summarize()


def test_schema_op_sequences_and_validates():
    svc, doc, a, b = setup_pair()
    reg = SchemaRegistry()
    reg.add(array_schema("list", {"number"}))
    reg.root = FieldSchema(FieldKind.OPTIONAL, {"list", "number"})
    tree_of(a).set_schema(reg)
    a.flush()
    doc.process_all()
    assert "list" in tree_of(b).schema.nodes
    tree_of(b).submit_change(make_insert([], "", 0, [leaf(5)]))
    b.flush()
    doc.process_all()
    assert tree_of(a).schema.check_forest(tree_of(a).forest) == []


def test_typed_view_reads_and_writes():
    svc, doc, a, b = setup_pair()
    view = tree_of(a).view
    view.set_root(build_node("todo", title=leaf("list"), items=[]))
    a.flush()
    doc.process_all()
    root_b = tree_of(b).view.root
    assert root_b.scalar("title") == "list"
    root_b.insert(0, ["first", "second"], key="items")
    b.flush()
    doc.process_all()
    items = tree_of(a).view.root.children("items")
    assert [i.value for i in items] == ["first", "second"]
    tree_of(a).view.root.set("title", "renamed")
    a.flush()
    doc.process_all()
    assert tree_of(b).view.root.scalar("title") == "renamed"


# --------------------------------------------------------------------------
# EditManager internals
# --------------------------------------------------------------------------

def test_editmanager_trunk_eviction():
    svc, doc, a, b = setup_pair()
    for i in range(10):
        tree_of(a).submit_change(make_insert([], "", i, [leaf(i)]))
        a.flush()
        if root_values(b):
            tree_of(b).submit_change(make_set_value([("", 0)], 100 + i))
            b.flush()
        doc.process_all()
    em = tree_of(a).em
    # MSN advanced with every round: the trunk must not retain all history.
    assert len(em.trunk) < 10
    assert em.trunk_base > 0
    assert root_values(a) == root_values(b)


def test_editmanager_peer_branch_fifo_pop():
    em = EditManager()
    f = Forest()
    c1 = make_insert([], "", 0, [leaf(1)])
    c2 = make_insert([], "", 1, [leaf(2)])
    t1 = em.add_sequenced("P", "P:1", [clone_change(c1)], ref_seq=0, seq=1)
    t2 = em.add_sequenced("P", "P:2", [clone_change(c2)], ref_seq=0, seq=2)
    apply_node_change(f.root, t1[0])
    apply_node_change(f.root, t2[0])
    assert [n.value for n in f.root_field] == [1, 2]
    # Branch base advance pops P's own commits in FIFO order.
    em.add_sequenced("P", "P:3", [make_insert([], "", 2, [leaf(3)])], ref_seq=2, seq=3)
    assert [rev for rev, _ in em.peers["P"].inflight] == ["P:3"]


def test_editmanager_cross_peer_interleave():
    """P and Q edit concurrently without seeing each other (refSeq pinned);
    trunk versions must thread each through the other deterministically."""
    em = EditManager()
    f = Forest()
    base = make_insert([], "", 0, [leaf(0), leaf(1), leaf(2)])
    apply_node_change(f.root, em.add_sequenced("S", "S:1", [base], ref_seq=0, seq=1)[0])
    p = make_insert([], "", 1, [leaf(10)])
    q = make_remove([], "", 1, 1)
    apply_node_change(f.root, em.add_sequenced("P", "P:1", [p], ref_seq=1, seq=2)[0])
    apply_node_change(f.root, em.add_sequenced("Q", "Q:1", [q], ref_seq=1, seq=3)[0])
    # P inserted before node 1; Q removed old node 1 (value 1): [0, 10, 2]
    assert [n.value for n in f.root_field] == [0, 10, 2]


# --------------------------------------------------------------------------
# randomized convergence farm (the fuzz oracle)
# --------------------------------------------------------------------------

def _random_edit(rng: random.Random, c: ContainerRuntime):
    vals = root_values(c)
    n = len(vals)
    kind = rng.choice(["ins", "ins", "rm", "set"] if n else ["ins"])
    if kind == "ins":
        tree_of(c).submit_change(
            make_insert([], "", rng.randint(0, n), [leaf(rng.randint(0, 999))])
        )
    elif kind == "rm":
        i = rng.randint(0, n - 1)
        tree_of(c).submit_change(make_remove([], "", i, rng.randint(1, min(2, n - i))))
    else:
        tree_of(c).submit_change(make_set_value([("", rng.randint(0, n - 1))], rng.randint(0, 999)))


def test_convergence_farm():
    """Randomized multi-client rounds with partial flushes and interleaved
    delivery — the reference's conflict-farm pattern
    (merge-tree client.conflictFarm.spec.ts, ddsFuzzHarness synchronize)."""
    for seed in range(8):
        rng = random.Random(seed)
        svc = LocalService()
        doc = svc.document(f"farm{seed}")
        cs = [make_container(doc, f"C{i}") for i in range(3)]
        doc.process_all()
        for _ in range(12):
            for c in cs:
                for _ in range(rng.randint(0, 2)):
                    _random_edit(rng, c)
                if rng.random() < 0.8:
                    c.flush()
            if rng.random() < 0.6:
                doc.process_all()
        for c in cs:
            c.flush()
        doc.process_all()
        states = [tree_of(c).forest.to_json() for c in cs]
        assert states[0] == states[1] == states[2], f"divergence at seed {seed}"
        assert all(c.pending_op_count == 0 for c in cs)
