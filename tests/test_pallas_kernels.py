"""Pallas long-document position resolution, differentially against the
jnp oracle (interpreter mode — tests run on the CPU mesh)."""

from __future__ import annotations

import numpy as np
import pytest

from fluidframework_tpu.ops.pallas_kernels import (
    resolve_positions_blocked,
    resolve_positions_pallas,
    resolve_positions_reference,
)


def random_case(rng, n_segs, n_queries, max_len=9, vis_p=0.7):
    lens = rng.integers(0, max_len, size=n_segs).astype(np.int32)
    lens = np.where(rng.random(n_segs) < vis_p, lens, 0).astype(np.int32)
    total = int(lens.sum())
    qs = rng.integers(0, max(total, 1) + 3, size=n_queries).astype(np.int32)
    return lens, qs


@pytest.mark.parametrize("n_segs", [1, 7, 128, 1024, 1500, 4096])
def test_pallas_resolve_matches_reference(n_segs):
    rng = np.random.default_rng(n_segs)
    for trial in range(4):
        lens, qs = random_case(rng, n_segs, n_queries=37)
        ri, ro, rh = resolve_positions_reference(lens, qs)
        pi, po, ph = resolve_positions_pallas(lens, qs, interpret=True)
        np.testing.assert_array_equal(np.asarray(ri), np.asarray(pi))
        np.testing.assert_array_equal(np.asarray(ro), np.asarray(po))
        np.testing.assert_array_equal(np.asarray(rh), np.asarray(ph))


def test_pallas_resolve_misses_are_zero():
    lens = np.asarray([3, 0, 2], np.int32)  # total visible = 5
    qs = np.asarray([0, 2, 3, 4, 5, 99], np.int32)
    pi, po, ph = resolve_positions_pallas(lens, qs, interpret=True)
    ri, ro, rh = resolve_positions_reference(lens, qs)
    np.testing.assert_array_equal(np.asarray(pi), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(ph), np.asarray(rh))
    # In-range queries land in the right segment with the right offset
    # (queries 0,2 in segment 0; 3,4 in segment 2; 5 is one past the end).
    assert list(np.asarray(pi))[:4] == [0, 0, 2, 2]
    assert list(np.asarray(po))[:4] == [0, 2, 0, 1]
    # Misses (q >= total) report (0, 0).
    assert int(pi[4]) == 0 and int(po[4]) == 0 and int(ph[4]) == 0
    assert int(pi[5]) == 0 and int(po[5]) == 0 and int(ph[5]) == 0
    assert list(np.asarray(ph))[:4] == [1, 1, 1, 1]


def test_pallas_resolve_all_invisible():
    lens = np.zeros(256, np.int32)
    qs = np.asarray([0, 1, 2], np.int32)
    pi, po, ph = resolve_positions_pallas(lens, qs, interpret=True)
    assert not np.asarray(pi).any() and not np.asarray(po).any()
    assert not np.asarray(ph).any()


@pytest.mark.parametrize("seed", [11, 12, 13, 14])
def test_resolve_triple_parity_fuzz(seed):
    """The three entries — ``resolve_positions_pallas`` (interpret),
    ``resolve_positions_blocked`` (the backend-dispatching entry the
    segment-parallel kernel calls behind its flag), and
    ``resolve_positions_reference`` (the oracle) — agree on random
    perspectives, out-of-range and NEGATIVE query positions included
    (the seg path queries local coordinates that go negative for earlier
    shards' positions)."""
    rng = np.random.default_rng(seed)
    for _trial in range(6):
        # Sizes draw from a fixed palette: resolve_positions_* jit-compile
        # per (S, Q) signature, so free-random sizes would turn the fuzz
        # into a compile benchmark.
        n_segs = int(rng.choice([1, 65, 517, 899]))
        lens, qs = random_case(rng, n_segs, n_queries=41)
        # Mix in out-of-range high and negative queries deliberately.
        extra = np.asarray(
            [-1, -7, int(lens.sum()), int(lens.sum()) + 5], np.int32
        )
        qs = np.concatenate([qs, extra])
        ri, ro, rh = resolve_positions_reference(lens, qs)
        bi, bo, bh = resolve_positions_blocked(lens, qs)
        pi, po, ph = resolve_positions_pallas(lens, qs, interpret=True)
        for got_i, got_o, got_h in ((bi, bo, bh), (pi, po, ph)):
            np.testing.assert_array_equal(np.asarray(ri), np.asarray(got_i))
            np.testing.assert_array_equal(np.asarray(ro), np.asarray(got_o))
            np.testing.assert_array_equal(
                np.asarray(rh).astype(np.int32),
                np.asarray(got_h).astype(np.int32),
            )
        # Misses never report a hit; hits land inside their segment.
        hits = np.asarray(rh).astype(bool)
        if hits.any():
            gi = np.asarray(ri)[hits]
            off = np.asarray(ro)[hits]
            assert (off >= 0).all() and (off < lens[gi]).all()
        assert not np.asarray(rh)[np.asarray(qs) < 0].any()


def test_blocked_is_reference_off_tpu():
    """On non-TPU backends the blocked entry must BE the jnp oracle (the
    CPU test mesh semantics the segment-parallel flag relies on)."""
    rng = np.random.default_rng(0)
    lens, qs = random_case(rng, 333, 17)
    bi, bo, bh = resolve_positions_blocked(lens, qs)
    ri, ro, rh = resolve_positions_reference(lens, qs)
    np.testing.assert_array_equal(np.asarray(bi), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(bo), np.asarray(ro))
    np.testing.assert_array_equal(np.asarray(bh), np.asarray(rh))
