"""Pallas long-document position resolution, differentially against the
jnp oracle (interpreter mode — tests run on the CPU mesh)."""

from __future__ import annotations

import numpy as np
import pytest

from fluidframework_tpu.ops.pallas_kernels import (
    resolve_positions_pallas,
    resolve_positions_reference,
)


def random_case(rng, n_segs, n_queries, max_len=9, vis_p=0.7):
    lens = rng.integers(0, max_len, size=n_segs).astype(np.int32)
    lens = np.where(rng.random(n_segs) < vis_p, lens, 0).astype(np.int32)
    total = int(lens.sum())
    qs = rng.integers(0, max(total, 1) + 3, size=n_queries).astype(np.int32)
    return lens, qs


@pytest.mark.parametrize("n_segs", [1, 7, 128, 1024, 1500, 4096])
def test_pallas_resolve_matches_reference(n_segs):
    rng = np.random.default_rng(n_segs)
    for trial in range(4):
        lens, qs = random_case(rng, n_segs, n_queries=37)
        ri, ro, rh = resolve_positions_reference(lens, qs)
        pi, po, ph = resolve_positions_pallas(lens, qs, interpret=True)
        np.testing.assert_array_equal(np.asarray(ri), np.asarray(pi))
        np.testing.assert_array_equal(np.asarray(ro), np.asarray(po))
        np.testing.assert_array_equal(np.asarray(rh), np.asarray(ph))


def test_pallas_resolve_misses_are_zero():
    lens = np.asarray([3, 0, 2], np.int32)  # total visible = 5
    qs = np.asarray([0, 2, 3, 4, 5, 99], np.int32)
    pi, po, ph = resolve_positions_pallas(lens, qs, interpret=True)
    ri, ro, rh = resolve_positions_reference(lens, qs)
    np.testing.assert_array_equal(np.asarray(pi), np.asarray(ri))
    np.testing.assert_array_equal(np.asarray(ph), np.asarray(rh))
    # In-range queries land in the right segment with the right offset
    # (queries 0,2 in segment 0; 3,4 in segment 2; 5 is one past the end).
    assert list(np.asarray(pi))[:4] == [0, 0, 2, 2]
    assert list(np.asarray(po))[:4] == [0, 2, 0, 1]
    # Misses (q >= total) report (0, 0).
    assert int(pi[4]) == 0 and int(po[4]) == 0 and int(ph[4]) == 0
    assert int(pi[5]) == 0 and int(po[5]) == 0 and int(ph[5]) == 0
    assert list(np.asarray(ph))[:4] == [1, 1, 1, 1]


def test_pallas_resolve_all_invisible():
    lens = np.zeros(256, np.int32)
    qs = np.asarray([0, 1, 2], np.int32)
    pi, po, ph = resolve_positions_pallas(lens, qs, interpret=True)
    assert not np.asarray(pi).any() and not np.asarray(po).any()
    assert not np.asarray(ph).any()
