"""loadgen: the multi-process traffic plant.

Tier-1 half: schedule determinism (the ChaosSchedule contract), lossless
cross-process histogram shipping, the scoped-presence fanout path the
workers exercise, and one REAL smoke — two worker OS processes over real
TCP through a real netserver + fleet stack, all four phase barriers, and
the byte-identity verdict for both fleet families.

The seeded multi-run matrix (3 seeds, 4 workers, full doc matrix) rides
behind ``-m slow``; ``bench.py --config loadgen`` commits the full-size
artifact run.
"""

from __future__ import annotations

import json

import pytest

from fluidframework_tpu.fanout.plane import FanoutPlane
from fluidframework_tpu.loadgen.coordinator import run_loadgen
from fluidframework_tpu.loadgen.schedule import (
    DocSpec,
    LoadSchedule,
    make_load_schedule,
)
from fluidframework_tpu.utils.telemetry import Histogram


def _docs():
    return [
        DocSpec(doc_id="string0", family="string", shard=0),
        DocSpec(doc_id="tree0", family="tree", shard=0),
        DocSpec(doc_id="map0", family="map", shard=0),
    ]


# ---------------------------------------------------------------- schedule
def test_schedule_same_seed_is_bit_identical():
    a = make_load_schedule(42, 4, _docs())
    b = make_load_schedule(42, 4, _docs())
    assert a.to_json() == b.to_json()
    # And a different seed really changes the script.
    c = make_load_schedule(43, 4, _docs())
    assert a.to_json() != c.to_json()


def test_schedule_json_roundtrip():
    sched = make_load_schedule(7, 3, _docs())
    back = LoadSchedule.from_json(sched.to_json())
    assert back.to_json() == sched.to_json()
    assert [w.seed for w in back.workers] == [w.seed for w in sched.workers]
    assert [d.doc_id for d in back.docs] == [d.doc_id for d in sched.docs]


def test_schedule_interests_are_strict_subsets():
    """Every worker subscribes to a non-empty STRICT subset of the scope
    universe — publishing across the full universe then guarantees the
    fanout plane's scoped-drop path fires on every run."""
    sched = make_load_schedule(11, 8, _docs())
    universe = set(sched.scopes)
    for w in sched.workers:
        interests = set(w.interests)
        assert interests, f"worker {w.worker_id} has no interests"
        assert interests < universe, (
            f"worker {w.worker_id} subscribed to the whole universe"
        )


# -------------------------------------------------------- histogram wire
def test_histogram_wire_roundtrip_and_merge_is_lossless():
    """The worker->coordinator shipping path: to_wire over JSON, from_wire,
    merge — bucket-exact, so merged percentiles equal a single-process
    histogram over the union of samples."""
    union = Histogram()
    parts = []
    for k in range(3):
        h = Histogram()
        for i in range(50):
            v = (k * 50 + i + 1) * 1e-4
            h.record(v)
            union.record(v)
        parts.append(h)
    merged = None
    for h in parts:
        wire = json.loads(json.dumps(h.to_wire()))  # the control socket
        got = Histogram.from_wire(wire)
        assert got.snapshot() == h.snapshot()
        merged = got if merged is None else merged.merge(got)
    assert merged.count == union.count == 150
    got, want = merged.snapshot(), union.snapshot()
    # ``sum`` accumulates in a different order across the three parts —
    # identical up to float addition reassociation; buckets are exact.
    assert got.pop("sum") == pytest.approx(want.pop("sum"))
    assert got == want


def test_histogram_empty_wire_roundtrip():
    h = Histogram.from_wire(json.loads(json.dumps(Histogram().to_wire())))
    assert h.count == 0
    assert h.percentile(0.5) is None


# ------------------------------------------------------- scoped presence
def _signal_sink(plane):
    chunks = []
    peer = plane.new_peer(sink=chunks.append)
    return peer, chunks


def _drain_signals(plane, peer, chunks):
    plane.drain_virtual(peer)
    out = []
    for chunk in chunks:
        for line in bytes(chunk).splitlines():
            msg = json.loads(line)
            if msg.get("t") == "signal":
                out.append(msg["contents"])
    chunks.clear()
    return out


def test_scoped_presence_filters_by_interest_set():
    plane = FanoutPlane()
    cursor_peer, cursor_chunks = _signal_sink(plane)
    editor_peer, editor_chunks = _signal_sink(plane)
    firehose_peer, firehose_chunks = _signal_sink(plane)
    plane.add_signal_peer("d", cursor_peer, interests=["cursor"])
    plane.add_signal_peer("d", editor_peer, interests=["editor"])
    plane.add_signal_peer("d", firehose_peer)  # legacy unscoped firehose

    plane.publish_signal("d", "c1", {"scope": "cursor", "n": 1},
                         scope="cursor")
    assert _drain_signals(plane, cursor_peer, cursor_chunks) == [
        {"scope": "cursor", "n": 1}
    ]
    assert _drain_signals(plane, editor_peer, editor_chunks) == []
    assert _drain_signals(plane, firehose_peer, firehose_chunks) == [
        {"scope": "cursor", "n": 1}
    ]
    assert plane.stats()["presence_scope_drops"] == 1

    # Unscoped signals (joins/leaves/broadcast presence) reach everyone.
    plane.publish_signal("d", "c1", {"n": 2})
    for peer, chunks in (
        (cursor_peer, cursor_chunks),
        (editor_peer, editor_chunks),
        (firehose_peer, firehose_chunks),
    ):
        assert _drain_signals(plane, peer, chunks) == [{"n": 2}]
    assert plane.stats()["presence_scope_drops"] == 1


def test_scoped_presence_interests_replace_in_place():
    plane = FanoutPlane()
    peer, chunks = _signal_sink(plane)
    plane.add_signal_peer("d", peer, interests=["cursor"])
    plane.publish_signal("d", "c1", {"n": 1}, scope="editor")
    assert _drain_signals(plane, peer, chunks) == []
    plane.add_signal_peer("d", peer, interests=["editor"])  # re-subscribe
    plane.publish_signal("d", "c1", {"n": 2}, scope="editor")
    assert _drain_signals(plane, peer, chunks) == [{"n": 2}]
    plane.publish_signal("d", "c1", {"n": 3}, scope="cursor")
    assert _drain_signals(plane, peer, chunks) == []
    assert plane.stats()["presence_scope_drops"] == 2


# --------------------------------------------------------------- the plant
def _assert_report_shape(report, n_workers):
    assert report["workers"] == n_workers
    for phase in ("ramp", "steady"):
        assert report["phases"][phase]["count"] > 0, (
            f"no latency samples in {phase}: {report['phases']}"
        )
    assert report["convergence"]["verdict"] == "byte-identical"
    assert report["scribe"]["double_acks"] == 0
    assert report["client"]["ops_sequenced"] > 0
    assert report["presence"]["foreign"] == 0


def test_loadgen_smoke_two_workers_real_tcp(tmp_path):
    """2 worker processes x short schedule over real TCP through a real
    netserver: every phase barrier observed, merged histograms non-empty,
    both fleet families byte-converged against host oracles."""
    report = run_loadgen(
        str(tmp_path), seed=1117, n_workers=2, n_shards=1,
        doc_matrix={"string": 1, "tree": 1, "map": 1},
        ramp_ops=3, steady_ops=8, boots=2, deadline_s=240.0,
    )
    _assert_report_shape(report, 2)
    conv = report["convergence"]["converged_docs"]
    assert conv["string"] == 1 and conv["tree"] == 1 and conv["map"] == 1
    assert report["boot_storm"]["cold"]["count"] > 0
    assert report["boot_storm"]["not_modified"]["count"] > 0
    # The boot storm really hit the historian's conditional-GET path.
    assert report["boot_storm"]["historian"]["not_modified_304"] > 0
    assert report["presence"]["fanout_scope_drops"] > 0


@pytest.mark.slow
@pytest.mark.parametrize("seed", [2, 3, 5])
def test_loadgen_matrix_three_seeds(tmp_path, seed):
    """Longer seeded matrix: 4 workers, 2 shards, every channel family."""
    report = run_loadgen(
        str(tmp_path), seed=seed, n_workers=4, n_shards=2,
        ramp_ops=6, steady_ops=18, boots=4, deadline_s=480.0,
    )
    _assert_report_shape(report, 4)
    conv = report["convergence"]["converged_docs"]
    for family in ("string", "tree", "map", "matrix", "chan_string"):
        assert conv[family] >= 1, f"{family} missing from convergence: {conv}"
