"""SharedMatrix: permutation convergence, LWW/FWW cells, handle remapping."""

import random

import pytest

from fluidframework_tpu.dds.shared_matrix import SharedMatrix
from fluidframework_tpu.server.local_service import LocalDocument


def make_matrices(doc, n):
    ms = []
    for i in range(n):
        m = SharedMatrix(client_id=f"c{i}")
        doc.connect(m.client_id, m.process)
        ms.append(m)
    doc.process_all()
    return ms


def pump(doc, ms):
    moved = True
    while moved:
        moved = False
        for m in ms:
            for msg in m.take_outbox():
                doc.submit(msg)
                moved = True
        if doc.pending_count:
            doc.process_all()
            moved = True


class TestSharedMatrix:
    def test_basic_grid(self):
        doc = LocalDocument("d")
        (a,) = make_matrices(doc, 1)
        a.insert_rows(0, 2)
        a.insert_cols(0, 3)
        pump(doc, [a])
        a.set_cell(0, 0, "x")
        a.set_cell(1, 2, "y")
        pump(doc, [a])
        assert a.to_grid() == [["x", None, None], [None, None, "y"]]

    def test_optimistic_cell_read_before_ack(self):
        doc = LocalDocument("d")
        (a,) = make_matrices(doc, 1)
        a.insert_rows(0, 1)
        a.insert_cols(0, 1)
        pump(doc, [a])
        a.set_cell(0, 0, 42)
        assert a.get_cell(0, 0) == 42  # pending overlay
        pump(doc, [a])
        assert a.get_cell(0, 0) == 42  # consensus after ack

    def test_concurrent_row_inserts_converge(self):
        doc = LocalDocument("d")
        a, b = make_matrices(doc, 2)
        a.insert_cols(0, 1)
        pump(doc, [a, b])
        a.insert_rows(0, 1)
        b.insert_rows(0, 1)
        pump(doc, [a, b])
        a.set_cell(0, 0, "top")
        b.set_cell(1, 0, "bottom")
        pump(doc, [a, b])
        assert a.to_grid() == b.to_grid() == [["top"], ["bottom"]]

    def test_lww_cell_conflict(self):
        doc = LocalDocument("d")
        a, b = make_matrices(doc, 2)
        a.insert_rows(0, 1)
        a.insert_cols(0, 1)
        pump(doc, [a, b])
        a.set_cell(0, 0, "first")
        b.set_cell(0, 0, "second")  # sequenced later -> LWW winner
        pump(doc, [a, b])
        assert a.get_cell(0, 0) == b.get_cell(0, 0) == "second"

    def test_fww_cell_conflict(self):
        doc = LocalDocument("d")
        a, b = make_matrices(doc, 2)
        a.insert_rows(0, 1)
        a.insert_cols(0, 1)
        pump(doc, [a, b])
        a.switch_to_fww()
        b.switch_to_fww()
        a.set_cell(0, 0, "first")
        b.set_cell(0, 0, "second")  # concurrent (refSeq < a's write) -> loses
        pump(doc, [a, b])
        assert a.cells == b.cells
        assert a.get_cell(0, 0) == "first"
        # A later non-concurrent write still wins under FWW.
        b.set_cell(0, 0, "third")
        pump(doc, [a, b])
        assert a.get_cell(0, 0) == b.get_cell(0, 0) == "third"

    def test_remove_rows_with_concurrent_cell_write(self):
        doc = LocalDocument("d")
        a, b = make_matrices(doc, 2)
        a.insert_rows(0, 2)
        a.insert_cols(0, 1)
        pump(doc, [a, b])
        a.remove_rows(0, 1)
        b.set_cell(0, 0, "doomed")  # writes into the removed row
        pump(doc, [a, b])
        assert a.to_grid() == b.to_grid()
        assert a.row_count == 1


@pytest.mark.parametrize("seed", range(10))
def test_matrix_farm_convergence(seed):
    """Concurrent row/col inserts/removes + cell writes with randomized
    delivery must converge to identical grids on all replicas."""
    rng = random.Random(seed)
    doc = LocalDocument("d")
    ms = make_matrices(doc, rng.randint(2, 3))
    for _round in range(rng.randint(3, 7)):
        for m in ms:
            for _ in range(rng.randint(0, 3)):
                r = rng.random()
                nrows = len(m.rows.handles(2**30 - 1, m.short_client))
                ncols = len(m.cols.handles(2**30 - 1, m.short_client))
                if r < 0.25 or nrows == 0:
                    m.insert_rows(rng.randint(0, nrows), rng.randint(1, 2))
                elif r < 0.45 or ncols == 0:
                    m.insert_cols(rng.randint(0, ncols), rng.randint(1, 2))
                elif r < 0.55 and nrows > 1:
                    p = rng.randint(0, nrows - 1)
                    m.remove_rows(p, 1)
                elif r < 0.62 and ncols > 1:
                    p = rng.randint(0, ncols - 1)
                    m.remove_cols(p, 1)
                elif ncols > 0 and nrows > 0:
                    m.set_cell(
                        rng.randint(0, nrows - 1), rng.randint(0, ncols - 1),
                        rng.randint(0, 999),
                    )
            if rng.random() < 0.7:
                for msg in m.take_outbox():
                    doc.submit(msg)
        doc.process_some(rng.randint(0, doc.pending_count))
    pump(doc, ms)
    grids = [m.to_grid() for m in ms]
    for g in grids[1:]:
        assert g == grids[0], f"grid divergence (seed {seed})"
