"""Framework layer tests: FluidContainer/schema bootstrap, DataObject,
presence signals, undo-redo stacks, attributor, service client.

Mirrors the reference's fluid-static, aqueduct, presence, undo-redo, and
attributor test suites (SURVEY §2.4)."""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.tree.changeset import make_insert, make_set_value
from fluidframework_tpu.dds.tree.schema import leaf
from fluidframework_tpu.framework import (
    ContainerSchema,
    DataObjectFactory,
    LocalServiceClient,
    OpStreamAttributor,
    Presence,
    UndoRedoStackManager,
)


SCHEMA = ContainerSchema(
    initial_objects={"text": "sharedString", "meta": "sharedMap", "doc": "sharedTree"}
)


@pytest.fixture
def client():
    return LocalServiceClient()


def process(client):
    client.service.process_all()


# --------------------------------------------------------------------------
# fluid-static + service client
# --------------------------------------------------------------------------

def test_create_and_get_container(client):
    fc, services = client.create_container(SCHEMA, "doc1")
    process(client)
    objs = fc.initial_objects
    assert set(objs) == {"text", "meta", "doc"}
    objs["text"].insert_text(0, "hi")
    objs["meta"].set("k", 1)
    fc.flush()
    process(client)

    fc2, services2 = client.get_container("doc1", SCHEMA)
    process(client)
    objs2 = fc2.initial_objects
    assert objs2["text"].text == "hi"
    assert objs2["meta"].get("k") == 1
    assert set(services2["audience"].members()) >= {services2["audience"].my_id}


def test_schema_mismatch_rejected(client):
    client.create_container(SCHEMA, "doc1")
    process(client)
    bad = ContainerSchema(initial_objects={"text": "sharedMap"})
    with pytest.raises(ValueError, match="schema expects"):
        client.get_container("doc1", bad)


def test_is_dirty_tracks_pending(client):
    fc, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    fc.initial_objects["meta"].set("x", 1)
    fc.flush()
    assert fc.is_dirty
    process(client)
    assert not fc.is_dirty


# --------------------------------------------------------------------------
# aqueduct
# --------------------------------------------------------------------------

def test_data_object_factory_roundtrip(client):
    inited = []
    factory = DataObjectFactory(
        "todoList",
        initial_channels={"items": "sharedString"},
        initializing_first_time=lambda obj: (
            obj.root.set("title", "untitled"),
            inited.append(obj.id),
        ),
    )
    fc, _ = client.create_container(ContainerSchema(initial_objects={}), "doc1")
    obj = factory.create(fc.container.runtime, "todo1")
    assert inited == ["todo1"]
    obj.channel("items").insert_text(0, "milk")
    fc.flush()
    process(client)
    assert obj.root.get("title") == "untitled"

    fc2, _ = client.get_container("doc1", ContainerSchema(initial_objects={}))
    process(client)
    obj2 = factory.get(fc2.container.runtime, "todo1")
    assert obj2.root.get("title") == "untitled"
    assert obj2.channel("items").text == "milk"


# --------------------------------------------------------------------------
# presence
# --------------------------------------------------------------------------

def test_presence_typed_workspaces(client):
    """Typed value managers + notifications + attendees (ref
    presence-definitions latestTypes/latestMapTypes/notificationsTypes)."""
    fc1, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    p1 = Presence(fc1.container)
    ws1 = p1.states_workspace("app")
    cursor1 = ws1.latest("cursor", initial=[0, 0])
    sel1 = ws1.latest_map("selection")
    sel1.set_item("start", 3)
    sel1.set_item("end", 9)
    ws1.flush()

    fc2, _ = client.get_container("doc1", SCHEMA)
    process(client)
    p2 = Presence(fc2.container)
    ws2 = p2.states_workspace("app")
    cursor2 = ws2.latest("cursor")
    sel2 = ws2.latest_map("selection")
    c1_id = fc1.container.runtime.client_id

    # Join catch-up delivered the typed state.
    assert cursor2.get_remote(c1_id) == [0, 0]
    assert sel2.get_remote(c1_id) == {"start": 3, "end": 9}
    assert c1_id in p2.attendees()

    # Updates flow with events.
    seen = []
    cursor2.on_updated(lambda cid, v: seen.append((cid, v)))
    cursor1.local = [7, 8]
    ws1.flush()
    assert cursor2.get_remote(c1_id) == [7, 8]
    assert seen == [(c1_id, [7, 8])]

    # Notifications: fire-and-forget, never retained.
    n1 = p1.notifications_workspace("alerts")
    n2 = p2.notifications_workspace("alerts")
    pings = []
    n2.on_notification(lambda cid, name, payload: pings.append((name, payload)))
    n1.emit("ping", {"n": 1})
    assert pings == [("ping", {"n": 1})]

    # Attendee departure fires and clears state.
    left = []
    p2.on_attendee_left(left.append)
    p1.leave()
    assert left == [c1_id]
    assert cursor2.get_remote(c1_id) is None


def test_presence_attendee_left_on_disconnect_without_leave(client):
    """A crash/disconnect (sequenced LEAVE, no voluntary signal) departs
    the fabric: attendees drop and state clears."""
    fc1, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    p1 = Presence(fc1.container)
    p1.set_now("cursor", 9)
    fc2, _ = client.get_container("doc1", SCHEMA)
    process(client)
    p2 = Presence(fc2.container)
    c1 = fc1.container.runtime.client_id
    assert c1 in p2.attendees()
    left = []
    unsub = p2.on_attendee_left(left.append)
    fc1.disconnect()  # no p1.leave()
    process(client)
    assert left == [c1]
    assert c1 not in p2.attendees()
    assert p2.remote_states("cursor") == {}
    unsub()
    assert p2._left_listeners == []


def test_presence_stateless_member_visible_to_newcomer(client):
    fc1, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    p1 = Presence(fc1.container)  # no state set at all
    fc2, _ = client.get_container("doc1", SCHEMA)
    process(client)
    p2 = Presence(fc2.container)
    assert fc1.container.runtime.client_id in p2.attendees()


def test_presence_namespace_separator_escaped(client):
    fc1, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    fc2, _ = client.get_container("doc1", SCHEMA)
    process(client)
    p1, p2 = Presence(fc1.container), Presence(fc2.container)
    ws1 = p1.states_workspace("app")
    a = ws1.latest("sel:start")
    m = ws1.latest_map("sel")
    a.local = "latest-value"
    m.set_item("start", "map-value")
    ws1.flush()
    c1 = fc1.container.runtime.client_id
    ws2 = p2.states_workspace("app")
    assert ws2.latest("sel:start").get_remote(c1) == "latest-value"
    assert ws2.latest_map("sel").get_remote(c1) == {"start": "map-value"}


def test_presence_updates_and_join_catchup(client):
    fc1, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    p1 = Presence(fc1.container)
    p1.set("cursor", [1, 2])
    p1.flush()

    # A later client joins and receives existing state via the handshake.
    fc2, _ = client.get_container("doc1", SCHEMA)
    process(client)
    p2 = Presence(fc2.container)
    assert p2.remote_states("cursor") == {fc1.container.runtime.client_id: [1, 2]}

    # Batched updates: two sets -> one broadcast, latest value wins.
    got = []
    p1.on_update(lambda c, k, v: got.append((k, v)))
    p2.set("cursor", [5, 5])
    p2.set("cursor", [6, 6])
    p2.flush()
    assert p1.states("cursor")[fc2.container.runtime.client_id] == [6, 6]
    assert got == [("cursor", [6, 6])]

    # Leave clears state at peers; nothing ever hit the op log.
    p2.leave()
    assert p1.remote_states("cursor") == {}
    doc = client.service.document("doc1")
    assert all(m.type != "signal" for m in doc.sequencer.log)


# --------------------------------------------------------------------------
# undo-redo
# --------------------------------------------------------------------------

def test_undo_redo_map(client):
    fc, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    meta = fc.initial_objects["meta"]
    ur = UndoRedoStackManager()
    ur.capture_map_set(meta, "k", 1)
    ur.close_current_operation()
    ur.capture_map_set(meta, "k", 2)
    ur.close_current_operation()
    fc.flush(); process(client)
    assert meta.get("k") == 2
    ur.undo(); fc.flush(); process(client)
    assert meta.get("k") == 1
    ur.undo(); fc.flush(); process(client)
    assert meta.get("k") is None
    ur.redo(); fc.flush(); process(client)
    assert meta.get("k") == 1
    ur.redo(); fc.flush(); process(client)
    assert meta.get("k") == 2


def test_undo_string_insert_slides_under_concurrent_edits(client):
    fc, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    fc2, _ = client.get_container("doc1", SCHEMA)
    process(client)
    t1 = fc.initial_objects["text"]
    t2 = fc2.initial_objects["text"]
    ur = UndoRedoStackManager()
    t1.insert_text(0, "base ")
    fc.flush(); process(client)
    ur.capture_string_insert(t1, 5, "WORD")
    ur.close_current_operation()
    fc.flush(); process(client)
    # Remote edit before the tracked range: it must slide.
    t2.insert_text(0, ">>> ")
    fc2.flush(); process(client)
    assert t1.text == ">>> base WORD"
    ur.undo()
    fc.flush(); process(client)
    assert t1.text == t2.text == ">>> base "


def test_undo_string_remove_reinserts(client):
    fc, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    t = fc.initial_objects["text"]
    t.insert_text(0, "hello world")
    fc.flush(); process(client)
    ur = UndoRedoStackManager()
    ur.capture_string_remove(t, 5, 11)
    ur.close_current_operation()
    fc.flush(); process(client)
    assert t.text == "hello"
    ur.undo(); fc.flush(); process(client)
    assert t.text == "hello world"
    ur.redo(); fc.flush(); process(client)
    assert t.text == "hello"


def test_undo_tree_change(client):
    fc, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    tree = fc.initial_objects["doc"]
    ur = UndoRedoStackManager()
    tree.submit_change(make_insert([], "", 0, [leaf(1), leaf(2)]))
    fc.flush(); process(client)
    ur.capture_tree_change(tree, make_set_value([("", 0)], 99))
    ur.close_current_operation()
    fc.flush(); process(client)
    assert [n.value for n in tree.forest.root_field] == [99, 2]
    ur.undo(); fc.flush(); process(client)
    assert [n.value for n in tree.forest.root_field] == [1, 2]
    ur.redo(); fc.flush(); process(client)
    assert [n.value for n in tree.forest.root_field] == [99, 2]


# --------------------------------------------------------------------------
# attributor
# --------------------------------------------------------------------------

def test_attributor_records_and_roundtrips(client):
    fc, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    att = OpStreamAttributor()
    doc = client.service.document("doc1")
    doc.connect("attrib-listener", att.observe)
    fc.initial_objects["meta"].set("k", 1)
    fc.flush(); process(client)
    fc.initial_objects["text"].insert_text(0, "x")
    fc.flush(); process(client)
    assert len(att) >= 2
    op_seqs = [m.seq for m in doc.sequencer.log if m.type == "op"]
    who = att.get(op_seqs[0])
    assert who["client"] == fc.container.runtime.client_id

    # Summary codec roundtrip (interned + delta encoded).
    data = att.summarize()
    fresh = OpStreamAttributor()
    fresh.load(data)
    assert all(fresh.get(s) == att.get(s) for s in op_seqs)
    assert len(data["clients"]) <= 3  # interning collapsed repeat clients


def test_undo_insert_split_by_remote_insert(client):
    """A pending insert split before ack undoes BOTH fragments, leaving the
    foreign content intact (review regression: tracker kept only the first
    fragment)."""
    fc, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    fc2, _ = client.get_container("doc1", SCHEMA)
    process(client)
    t1, t2 = fc.initial_objects["text"], fc2.initial_objects["text"]
    ur = UndoRedoStackManager()
    ur.capture_string_insert(t1, 0, "abcdef")
    ur.close_current_operation()
    fc.flush()
    # Remote insert lands INSIDE the pending segment before it acks.
    t2.insert_text(0, "seed ")
    fc2.flush(); process(client)
    # (t2's insert at 0 lands before; craft a true split: t2 inserts into
    # the middle of t1's now-acked text.)
    assert t1.text == t2.text
    before = t1.text
    assert "abcdef" in before
    t2.insert_text(t2.text.index("abcdef") + 3, "XX")
    fc2.flush(); process(client)
    assert "abcXXdef" in t1.text
    ur.undo(); fc.flush(); process(client)
    assert t1.text == t2.text
    assert "abc" not in t1.text and "def" not in t1.text
    assert "XX" in t1.text and "seed " in t1.text


def test_undo_manager_releases_listeners(client):
    fc, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    t = fc.initial_objects["text"]
    ur = UndoRedoStackManager()
    for i in range(5):
        ur.capture_string_insert(t, 0, f"w{i} ")
        ur.close_current_operation()
        fc.flush(); process(client)
        ur.undo()
        fc.flush(); process(client)
        ur.capture_string_insert(t, 0, "k ")  # clears redo stack
        ur.close_current_operation()
        fc.flush(); process(client)
    ur.dispose()
    assert t._converged_listeners == []


def test_presence_latency_window_coalesces_updates(client):
    """allowableUpdateLatency (ref presenceDatastoreManager.ts:473): rapid
    updates coalesce into ONE signal, flushed when the tightest queued
    deadline lapses — never before, never manually."""
    fc1, _ = client.create_container(SCHEMA, "doc1")
    process(client)
    fc2, _ = client.get_container("doc1", SCHEMA)
    process(client)
    pa = Presence(fc1.container)
    pb = Presence(fc2.container)
    signals = []
    fc2.container.on_signal(
        lambda s: signals.append(s.contents)
        if isinstance(s.contents, dict) and s.contents.get("presence") == "update"
        else None
    )
    base = len(signals)
    # Three rapid cursor moves within a 100ms window + one looser update.
    pa.set("cursor", [1, 1], allowed_latency_s=0.1, now=0.0)
    pa.set("cursor", [2, 2], allowed_latency_s=0.1, now=0.01)
    pa.set("color", "red", allowed_latency_s=5.0, now=0.02)
    assert not pa.tick(now=0.05)          # inside every window: no signal
    assert len(signals) == base
    assert pa.tick(now=0.11)              # cursor window lapsed: ONE signal
    assert len(signals) == base + 1
    # Wire entries are [[epoch, n], value]: per-key writer revisions let
    # receivers drop stale/reordered signals (cursor was set twice -> n=2).
    states = signals[-1]["states"]
    assert set(states) == {"cursor", "color"}
    assert states["cursor"][0][1] == 2 and states["cursor"][1] == [2, 2]
    assert states["color"][0][1] == 1 and states["color"][1] == "red"
    assert pb.states("cursor")[pa._my_id()] == [2, 2]
    assert not pa.tick(now=10.0)          # queue drained: nothing more
