"""Tests for utils: id-compressor cluster semantics, telemetry, config.

Modeled on the reference's id-compressor suite behaviors
(packages/runtime/id-compressor/src/test/): local-then-final lifecycle,
eager finals, cross-client normalization, stable-ID round trips, and
deterministic finalization across replicas.
"""

import pytest

from fluidframework_tpu.utils import (
    CachedConfigProvider,
    IdCompressor,
    Logger,
    MonitoringContext,
    PerformanceEvent,
    SampledTelemetryHelper,
    create_child_logger,
)


class TestIdCompressor:
    def test_local_ids_are_negative_gen_counts(self):
        c = IdCompressor()
        assert c.generate_compressed_id() == -1
        assert c.generate_compressed_id() == -2

    def test_finalize_makes_op_space_final(self):
        c = IdCompressor()
        a, b = c.generate_compressed_id(), c.generate_compressed_id()
        rng = c.take_next_creation_range()
        assert (rng.first_gen_count, rng.last_gen_count) == (1, 2)
        c.finalize_creation_range(rng)
        assert c.normalize_to_op_space(a) == 0
        assert c.normalize_to_op_space(b) == 1
        # Unfinalized IDs stay local in op space.
        e = c.generate_compressed_id()
        if e < 0:  # not eager-finalized (capacity may cover it)
            assert c.normalize_to_op_space(e) == e

    def test_eager_finals_after_cluster_exists(self):
        c = IdCompressor(cluster_capacity=4)
        c.generate_compressed_id()
        c.finalize_creation_range(c.take_next_creation_range())
        # Cluster reserved capacity 1+4; next IDs land inside it already-final.
        nxt = c.generate_compressed_id()
        assert nxt >= 0
        assert c.decompress(nxt)  # own eager final decompresses

    def test_cross_client_normalization_and_stable_ids(self):
        a = IdCompressor()
        b = IdCompressor()
        ida = a.generate_compressed_id()
        rng = a.take_next_creation_range()
        # Total order: both replicas finalize A's range identically.
        a.finalize_creation_range(rng)
        b.finalize_creation_range(rng)
        wire = a.normalize_to_op_space(ida)
        assert wire >= 0
        got = b.normalize_to_session_space(wire, a.session_id)
        assert got == wire  # foreign finals stay final
        assert b.decompress(got) == a.decompress(ida)
        # B can route A's *local* wire form too (delivered before A finalized).
        got2 = b.normalize_to_session_space(-1, a.session_id)
        assert got2 == wire

    def test_recompress_round_trip(self):
        c = IdCompressor()
        i = c.generate_compressed_id()
        stable = c.decompress(i)
        assert c.recompress(stable) == i
        c.finalize_creation_range(c.take_next_creation_range())
        # After finalize, recompress returns the final form.
        assert c.recompress(stable) == c.normalize_to_op_space(i)

    def test_out_of_order_finalization_rejected(self):
        a = IdCompressor()
        a.generate_compressed_id()
        r1 = a.take_next_creation_range()
        a.generate_compressed_id()
        r2 = a.take_next_creation_range()
        b = IdCompressor()
        with pytest.raises(ValueError, match="out of order"):
            b.finalize_creation_range(r2)
        b.finalize_creation_range(r1)
        b.finalize_creation_range(r2)

    def test_deterministic_across_replicas(self):
        compressors = [IdCompressor() for _ in range(3)]
        ranges = []
        for c in compressors:
            for _ in range(5):
                c.generate_compressed_id()
            ranges.append(c.take_next_creation_range())
        for c in compressors:
            for r in ranges:
                c.finalize_creation_range(r)
        # Identical finalized state everywhere.
        states = [c.serialize(with_session=False) for c in compressors]
        assert states[0] == states[1] == states[2]

    def test_serialize_round_trip(self):
        c = IdCompressor()
        for _ in range(3):
            c.generate_compressed_id()
        c.finalize_creation_range(c.take_next_creation_range())
        c2 = IdCompressor.deserialize(c.serialize())
        assert c2.session_id == c.session_id
        assert c2.normalize_to_op_space(-1) == c.normalize_to_op_space(-1)
        assert c2.decompress(-3) == c.decompress(-3)

    def test_cluster_expansion_in_place(self):
        c = IdCompressor(cluster_capacity=2)
        for _ in range(2):
            c.generate_compressed_id()
        c.finalize_creation_range(c.take_next_creation_range())
        # Generate more than remaining capacity; expansion (same session owns
        # the newest final block) must keep final IDs contiguous.
        for _ in range(6):
            c.generate_compressed_id()
        c.finalize_creation_range(c.take_next_creation_range())
        finals = [c.normalize_to_op_space(-(g + 1)) for g in range(8)]
        assert finals == list(range(8))


class TestTelemetry:
    def test_child_logger_namespacing_and_properties(self):
        root = Logger("root", properties={"docId": "d1"})
        child = create_child_logger(root, "runtime", {"layer": "runtime"})
        child.generic("opApplied", count=3)
        (e,) = root.events
        assert e["eventName"] == "root:runtime:opApplied"
        assert e["docId"] == "d1" and e["layer"] == "runtime" and e["count"] == 3

    def test_performance_event_span(self):
        log = Logger()
        with PerformanceEvent(log, "load", docId="d"):
            pass
        (e,) = log.matching(category="performance")
        assert e["eventName"] == "load_end" and e["duration"] >= 0

    def test_performance_event_cancel_on_error(self):
        log = Logger()
        with pytest.raises(RuntimeError):
            with PerformanceEvent(log, "load"):
                raise RuntimeError("boom")
        (e,) = log.matching(category="error")
        assert e["eventName"] == "load_cancel" and "boom" in e["error"]

    def test_sampled_helper_aggregates(self):
        log = Logger()
        h = SampledTelemetryHelper(log, "applyOp", sample_every=10)
        for _ in range(25):
            h.record(0.001, bucket="insert")
        events = log.matching(eventName="applyOp")
        assert len(events) == 2  # two full samples of 10; 5 pending
        assert all(e["count"] == 10 for e in events)
        h.flush("insert")
        assert log.matching(eventName="applyOp")[-1]["count"] == 5


class TestConfig:
    def test_layered_typed_reads(self):
        cfg = CachedConfigProvider(
            {"FluidTpu.A": "true", "FluidTpu.N": "42"},
            {"FluidTpu.A": "false", "FluidTpu.B": 7},
        )
        assert cfg.get_bool("FluidTpu.A") is True  # first provider wins
        assert cfg.get_number("FluidTpu.N") == 42.0
        assert cfg.get_number("FluidTpu.B") == 7
        assert cfg.get_bool("FluidTpu.Missing", default=False) is False
        assert cfg.get_string("FluidTpu.A") == "true"

    def test_monitoring_context_child(self):
        mc = MonitoringContext(Logger("root"))
        child = mc.child("dds", docId="d9")
        child.logger.generic("x")
        (e,) = mc.logger.events
        assert e["eventName"] == "root:dds:x" and e["docId"] == "d9"
