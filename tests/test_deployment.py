"""Buildable deployment (VERDICT r4 next #8): the compose topology's
process set — netserver shards via the launcher + the fleet tier through
fleet_main's ACTUAL ``python -m`` __main__ path — boots as real OS
processes, carries ops end to end, and the packaging artifacts
(pyproject.toml, Dockerfile, deploy/compose.yaml) agree with each other.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

import pytest

from fluidframework_tpu.native.ingest_native import available
from fluidframework_tpu.server.launcher import launch, shard_index

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "JAX_PLATFORMS": "cpu"}


def test_compose_topology_smoke():
    """Boot the deploy/compose.yaml topology in miniature: 2 launcher-
    supervised netserver shard PROCESSES, writers editing through real
    TCP per shard_index routing, and one fleet_main PROCESS per shard
    (``python -m`` — the exact compose command) draining the firehose to
    a device engine and reporting converged texts."""
    if not available():
        pytest.skip("native ingest encoder unavailable")
    from fluidframework_tpu.dds.shared_string import SharedString
    from fluidframework_tpu.driver.network_driver import NetworkDeltaConnection

    doc_ids = ["doc0", "doc1", "doc2", "doc3"]
    dep = launch({"shards": [{"name": "s0"}, {"name": "s1"}]})
    fleets: list[subprocess.Popen] = []
    try:
        by_shard: dict[int, list[str]] = {0: [], 1: []}
        for d in doc_ids:
            by_shard[shard_index(d, 2)].append(d)
        assert all(by_shard.values()), by_shard

        # Writers: standalone SharedStrings over the REAL TCP delta stream
        # (the raw merge-tree wire the fleet tier's native encoder parses).
        expected: dict[str, str] = {}
        for d in doc_ids:
            _host, port, _http = dep.endpoint_for(d)
            ss = SharedString(client_id=f"w-{d}")
            conn = NetworkDeltaConnection(
                "127.0.0.1", port, d, ss.client_id, "write",
                listener=ss.process, nack_listener=None, signal_listener=None,
            )
            if conn.join_msg is not None:
                ss.process(conn.join_msg)
            conn.pump(block_s=0.2)
            ss.insert_text(0, f"content-{d}")
            for m in ss.take_outbox():
                conn.submit(m)
            conn.sync()
            conn.pump()
            expected[d] = ss.text
            assert expected[d] == f"content-{d}"
            conn.disconnect()

        for si, shard in enumerate(dep.shards):
            docs = ",".join(by_shard[si])
            fleets.append(subprocess.Popen(
                [sys.executable, "-m", "fluidframework_tpu.server.fleet_main",
                 "--port", str(shard.port), "--docs", docs,
                 # One op row per doc: exit only after EVERY doc's firehose
                 # catch-up landed (exiting at 1 races the other doc's
                 # in-flight catch-up bytes).
                 "--exit-after-rows", str(len(by_shard[si])),
                 "--platform", "cpu"],
                stdout=subprocess.PIPE, text=True, cwd=REPO, env=ENV,
            ))
        for si, proc in enumerate(fleets):
            out, _ = proc.communicate(timeout=180)
            assert proc.returncode == 0, out[-500:]
            status = json.loads(out.strip().splitlines()[-1])
            assert status["done"] and status["errors"] == 0
            for d in by_shard[si]:
                assert status["texts"][d] == expected[d], (si, d)
    finally:
        for proc in fleets:
            if proc.poll() is None:
                proc.kill()
        dep.stop()


def test_packaging_artifacts_agree():
    """pyproject + Dockerfile + compose reference one buildable image:
    every compose `python -m` module imports, console scripts resolve,
    and the Dockerfile builds the image name compose runs."""
    import importlib

    compose = open(os.path.join(REPO, "deploy", "compose.yaml")).read()
    dockerfile = open(os.path.join(REPO, "Dockerfile")).read()
    pyproject = open(os.path.join(REPO, "pyproject.toml")).read()

    images = set(re.findall(r"image:\s*(\S+)", compose))
    assert images == {"fluidframework-tpu:latest"}
    assert "fluidframework-tpu" in pyproject

    for mod in set(re.findall(r'"python",\s*"-m",\s*\n?\s*"([\w.]+)"', compose)):
        importlib.import_module(mod)

    # Console entry points resolve to real callables.
    for ep in re.findall(r'fftpu-\w+ = "([\w.]+):(\w+)"', pyproject):
        mod, fn = ep
        assert callable(getattr(importlib.import_module(mod), fn)), ep

    # The Dockerfile copies everything its build steps touch.
    for needed in ("pyproject.toml", "fluidframework_tpu", "native"):
        assert re.search(rf"COPY .*{needed}", dockerfile), needed
    assert "pip install" in dockerfile


def test_launcher_supervise_restarts_crashed_shard():
    """The compose `restart: unless-stopped` analog: kill a shard process;
    the supervisor restarts it and the endpoint keeps serving."""
    import socket
    import time

    dep = launch({"shards": [{"name": "s0"}]}, supervise=True)
    try:
        port = dep.shards[0].port
        dep.shards[0].proc.kill()
        deadline = time.time() + 30
        ok = False
        while time.time() < deadline:
            try:
                s = socket.create_connection(("127.0.0.1", dep.shards[0].port), timeout=2)
                s.close()
                ok = True
                break
            except OSError:
                time.sleep(0.3)
        assert ok, "shard did not come back after kill"
        assert dep.shards[0].port == port  # stable endpoint
    finally:
        dep.stop()


def test_launcher_restart_budget_detects_crash_loop():
    """A shard that keeps dying must trip the restart budget: the
    supervisor backs off between respawns, then stops respawning and marks
    the shard crash-looped in the manifest — never an unconditional
    immediate relaunch loop hammering the same ports forever."""
    import time

    dep = launch({
        "shards": [{"name": "s0"}],
        "restartBudget": 2,
        "crashWindowS": 120.0,
        "restartBackoffS": 0.05,
        "maxRestartBackoffS": 0.2,
    }, supervise=True)
    try:
        deadline = time.time() + 90
        while time.time() < deadline:
            with dep._lock:
                looped = dep.shards[0].crash_looped
                proc = dep.shards[0].proc
            if looped:
                break
            if proc is not None and proc.poll() is None:
                proc.kill()  # the "crash", repeatedly
            time.sleep(0.1)
        m = dep.manifest()["shards"][0]
        assert m["crashLooped"] is True, "budget never tripped"
        assert m["pid"] is None
        # Respawns stopped AT the budget (initial launch is not a crash).
        assert m["restarts"] <= 2
        time.sleep(1.0)  # and it STAYS down
        with dep._lock:
            s = dep.shards[0]
            assert s.proc is None or s.proc.poll() is not None
            assert s.restarts == m["restarts"]  # no further respawns
    finally:
        dep.stop()


def test_launcher_heartbeat_and_promote_revives_crash_looped_shard():
    """The supervisor's fast-recovery surface (ISSUE 12): it stamps a
    liveness heartbeat file (the beacon a standby controller watches),
    and ``promote`` revives a shard the restart budget gave up on —
    fresh budget window, same spawn machinery, same ports."""
    import tempfile
    import time

    from fluidframework_tpu.server.failover import read_heartbeat

    hb_path = os.path.join(tempfile.mkdtemp(), "launcher-heartbeat.json")
    dep = launch({
        "shards": [{"name": "s0"}],
        "restartBudget": 1,
        "crashWindowS": 120.0,
        "restartBackoffS": 0.05,
        "maxRestartBackoffS": 0.1,
        "heartbeatFile": hb_path,
        "heartbeatEveryS": 0.1,
    }, supervise=True)
    try:
        deadline = time.time() + 30
        while time.time() < deadline and not os.path.exists(hb_path):
            time.sleep(0.1)
        rec, fresh = read_heartbeat(hb_path, stale_after_s=10.0)
        assert fresh and rec["shards"][0]["name"] == "s0"

        # Crash past the budget -> crashLooped, supervisor stands down.
        deadline = time.time() + 90
        while time.time() < deadline:
            with dep._lock:
                looped = dep.shards[0].crash_looped
                proc = dep.shards[0].proc
            if looped:
                break
            if proc is not None and proc.poll() is None:
                proc.kill()
            time.sleep(0.1)
        assert dep.manifest()["shards"][0]["crashLooped"] is True
        assert dep.promote("nope") is False  # unknown shard

        # Promote: the shard comes back on its ports with a fresh budget.
        assert dep.promote("s0") is True
        m = dep.manifest()["shards"][0]
        assert m["pid"] is not None and m["crashLooped"] is False
        assert dep.promote("s0") is False  # alive: nothing to promote
        # The heartbeat keeps stamping the revived manifest.
        deadline = time.time() + 10
        while time.time() < deadline:
            rec, fresh = read_heartbeat(hb_path, stale_after_s=1.0)
            if fresh and rec["shards"][0]["pid"] == m["pid"]:
                break
            time.sleep(0.1)
        assert fresh and rec["shards"][0]["pid"] == m["pid"]
    finally:
        dep.stop()
