"""Summarization subsystem tests: summary trees, incremental handles,
election, heuristics, scribe ack/nack, snapshot boot.

Mirrors the reference's summary suites (container-runtime/src/summary tests
+ e2e summarization benchmarks' correctness assertions, SURVEY §3.5)."""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.driver import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container
from fluidframework_tpu.runtime.summary import (
    SummaryConfig,
    blob,
    count_nodes,
    handle,
    materialize,
    tree,
)
from fluidframework_tpu.server import LocalService


# --------------------------------------------------------------------------
# tree format unit tests
# --------------------------------------------------------------------------

def test_materialize_blobs_and_trees():
    t = tree({"a": blob(1), "b": tree({"c": blob({"x": 2})})})
    assert materialize(t, None) == {"a": 1, "b": {"c": {"x": 2}}}


def test_materialize_resolves_handles_against_prev():
    prev = {"a": 1, "b": {"c": {"x": 2}}}
    t = tree({"a": blob(10), "b": tree({"c": handle("b/c")})})
    assert materialize(t, prev) == {"a": 10, "b": {"c": {"x": 2}}}


def test_materialize_handle_errors():
    with pytest.raises(ValueError, match="no previous summary"):
        materialize(tree({"a": handle("a")}), None)
    with pytest.raises(ValueError, match="handle path"):
        materialize(tree({"a": handle("wrong/path")}), {"a": 1})
    with pytest.raises(ValueError, match="lacks"):
        materialize(tree({"a": handle("a")}), {"other": 1})


# --------------------------------------------------------------------------
# end-to-end harness
# --------------------------------------------------------------------------

@pytest.fixture
def env():
    svc = LocalService()
    return svc, LocalDocumentServiceFactory(svc)


def boot(env, extra_channels=()):
    svc, factory = env
    d = Container.create_detached(default_registry(), container_id="creator")
    ds = d.runtime.create_datastore("root")
    ds.create_channel("sharedString", "text")
    ds.create_channel("sharedMap", "meta")
    for ctype, cid in extra_channels:
        ds.create_channel(ctype, cid)
    d.attach("doc", factory, "creator")
    svc.process_all()
    return svc, factory, d


def load(factory, name, **kw):
    return Container.load("doc", factory, default_registry(), name, **kw)


def text_of(c):
    return c.runtime.datastore("root").get_channel("text")


def map_of(c):
    return c.runtime.datastore("root").get_channel("meta")


def test_summary_flow_ack_and_baseline(env):
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(max_ops=5))
    assert sm.is_elected()
    for i in range(6):
        text_of(d).insert_text(0, f"x{i}")
        d.runtime.flush()
    svc.process_all()
    assert d.runtime.ops_since_summary_ack >= 5
    assert sm.tick() is True
    assert sm.tick() is False  # one in flight at a time
    svc.process_all()
    assert sm.acked == 1
    assert d.runtime.last_summary_ref_seq is not None
    assert d.runtime.ops_since_summary_ack == 0
    # The scribe stored a materialized snapshot at the summary refSeq.
    doc = svc.document("doc")
    seq, snap = doc.latest_snapshot()
    assert seq == d.runtime.last_summary_ref_seq
    assert "runtime" in snap and "protocol" in snap


def test_incremental_handles_for_clean_channels(env):
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))
    # Round 1: both channels edited -> all blobs.
    text_of(d).insert_text(0, "hello")
    map_of(d).set("k", 1)
    d.runtime.flush()
    svc.process_all()
    assert sm.tick()
    svc.process_all()
    t1 = d.runtime.build_summary_tree()
    # Round 2: only the string changes -> the map summarizes as a handle.
    text_of(d).insert_text(0, "more ")
    d.runtime.flush()
    svc.process_all()
    t2 = d.runtime.build_summary_tree()
    channels = t2["entries"]["datastores"]["entries"]["root"]["entries"]["channels"]["entries"]
    assert channels["meta"]["type"] == "handle"
    assert channels["text"]["type"] == "blob"
    # And the full tick-produced summary materializes correctly server-side.
    assert sm.tick()
    svc.process_all()
    assert sm.acked == 2
    _, snap = svc.document("doc").latest_snapshot()
    ch = snap["runtime"]["datastores"]["root"]["channels"]
    assert ch["meta"]["summary"]["entries"] == {"k": 1}


def test_loader_boots_from_scribe_snapshot(env):
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))
    text_of(d).insert_text(0, "summarized")
    map_of(d).set("k", 7)
    d.runtime.flush()
    svc.process_all()
    assert sm.tick()
    svc.process_all()
    base = d.runtime.last_summary_ref_seq
    # Ops after the summary arrive as trailing deltas.
    text_of(d).insert_text(0, "post-")
    d.runtime.flush()
    svc.process_all()

    c2 = load(factory, "late")
    svc.process_all()
    assert c2.runtime.last_summary_ref_seq == base  # baseline from snapshot
    assert text_of(c2).text == text_of(d).text == "post-summarized"
    assert map_of(c2).get("k") == 7
    # The late client can itself produce an incremental summary.
    sm2 = c2.make_summary_manager(SummaryConfig(max_ops=1))
    assert not sm2.is_elected()  # creator (earlier join) is still elected


def test_election_moves_on_disconnect(env):
    svc, factory, d = boot(env)
    c2 = load(factory, "second")
    svc.process_all()
    sm1 = d.make_summary_manager(SummaryConfig(max_ops=1))
    sm2 = c2.make_summary_manager(SummaryConfig(max_ops=1))
    assert sm1.is_elected() and not sm2.is_elected()
    d.disconnect()
    svc.process_all()  # leave sequences
    assert sm2.is_elected()
    text_of(c2).insert_text(0, "z")
    c2.runtime.flush()
    svc.process_all()
    assert sm2.tick()
    svc.process_all()
    assert sm2.acked == 1


def test_scribe_nacks_unknown_handle(env):
    svc, factory, d = boot(env)
    nacks = []
    d.runtime.on_summary_nack = lambda c: nacks.append(c)
    from fluidframework_tpu.protocol.messages import MessageType

    d.runtime.submit_protocol_message(
        MessageType.SUMMARIZE, {"handle": "bogus", "refSeq": d.runtime.ref_seq}
    )
    svc.process_all()
    assert nacks and nacks[0]["error"] == "unknown upload handle"
    assert d.runtime.last_summary_ref_seq is None


def test_dropped_connection_unsticks_summary_manager(env):
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))
    text_of(d).insert_text(0, "a")
    d.runtime.flush()
    svc.process_all()
    assert sm.tick()
    # The connection drops with the summarize in flight: the manager is
    # released immediately (local nack) so it can never wedge...
    d.disconnect()
    assert sm._inflight_handle is None
    d.connect()
    svc.process_all()
    # ...and since the op had already reached the ordering service, it still
    # sequences: the scribe ack lands and advances every replica's baseline.
    assert d.runtime.last_summary_ref_seq is not None
    # The manager keeps working on the new connection.
    text_of(d).insert_text(0, "b")
    d.runtime.flush()
    svc.process_all()
    assert sm.tick()
    svc.process_all()
    assert sm.acked >= 1


def test_dynamic_channel_summarizes_as_blob_then_handle(env):
    """A channel attached after the last acked summary must upload as a blob
    (review regression: missing changed_seqs classified it clean, wedging
    the scribe in a nack loop); once snapshotted it may become a handle."""
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))
    text_of(d).insert_text(0, "x")
    d.runtime.flush()
    svc.process_all()
    assert sm.tick()
    svc.process_all()
    assert sm.acked == 1

    ds = d.runtime.datastore("root")
    ds.create_channel("sharedMap", "newmap")
    d.runtime.submit_channel_attach("root", "newmap")
    d.runtime.flush()
    svc.process_all()
    ch = d.runtime.build_summary_tree()["entries"]["datastores"]["entries"]["root"][
        "entries"
    ]["channels"]["entries"]
    assert ch["newmap"]["type"] == "blob"
    assert sm.tick()
    svc.process_all()
    assert sm.acked == 2  # scribe stored it; no nack loop
    _, snap = svc.document("doc").latest_snapshot()
    assert "newmap" in snap["runtime"]["datastores"]["root"]["channels"]
    # Untouched since that ack: next tree may reuse a handle for it.
    ch2 = d.runtime.build_summary_tree()["entries"]["datastores"]["entries"]["root"][
        "entries"
    ]["channels"]["entries"]
    assert ch2["newmap"]["type"] == "handle"


def test_summary_nack_retries_without_handles(env):
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))
    text_of(d).insert_text(0, "x")
    map_of(d).set("k", 1)
    d.runtime.flush()
    svc.process_all()
    assert sm.tick()
    svc.process_all()
    assert sm.acked == 1
    # Server-side snapshot loss: the next incremental summary's handles
    # cannot resolve -> scribe nacks -> manager retries with full blobs.
    svc.document("doc")._snapshots.clear()
    text_of(d).insert_text(0, "y")  # map stays clean -> handle in next tree
    d.runtime.flush()
    svc.process_all()
    assert sm.tick()
    svc.process_all()
    assert sm.acked == 1  # nacked
    assert d.runtime.last_summary_ref_seq is None  # baseline dropped
    assert sm.tick()  # retry uploads full blobs
    svc.process_all()
    assert sm.acked == 2
    _, snap = svc.document("doc").latest_snapshot()
    assert snap["runtime"]["datastores"]["root"]["channels"]["meta"]["summary"] is not None


# --------------------------------------------------------------------------
# heuristics + retry ladder + re-election (VERDICT r3 next #7)
# --------------------------------------------------------------------------

def test_time_trigger_summarizes_with_few_ops(env):
    """max_time_s fires a summary even below max_ops, once min_ops exist
    (ref ISummaryConfiguration maxTime/minOpsForLastSummary)."""
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(max_ops=100, max_time_s=60.0))
    text_of(d).insert_text(0, "a")
    d.runtime.flush()
    svc.process_all()
    assert not sm.tick(now=10.0)     # neither ops nor time due
    assert not sm.tick(now=69.0)     # still inside the window
    assert sm.tick(now=70.5)         # window elapsed, min_ops satisfied
    svc.process_all()
    assert sm.acked == 1
    # The clock baseline advances on ack: no immediate re-trigger.
    assert not sm.tick(now=71.0)


def test_time_trigger_requires_min_ops(env):
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(max_ops=100, max_time_s=5.0))
    assert not sm.tick(now=0.0)
    assert not sm.tick(now=1000.0)  # no ops at all: nothing to summarize


def test_ack_wait_timeout_counts_failure_and_backs_off(env):
    """An in-flight summary whose ack never arrives (stalled scribe) frees
    the manager after max_ack_wait_s and backs off through the ladder
    (ref maxAckWaitTime + retry schedule)."""
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(
        max_ops=1, max_ack_wait_s=30.0, retry_delays=(0.0, 10.0, 60.0),
    ))
    text_of(d).insert_text(0, "a")
    d.runtime.flush()
    svc.process_all()
    # Stall the scribe: the summarize op sequences but is never acked.
    doc = svc.document("doc")
    real_scribe = doc._scribe_process_summarize
    doc._scribe_process_summarize = lambda msg: None
    assert sm.tick(now=0.0)
    svc.process_all()  # op delivered; no ack produced
    doc._scribe_process_summarize = real_scribe
    assert not sm.tick(now=10.0)          # still waiting inside ack window
    assert sm.failures == 0
    assert not sm.tick(now=31.0)          # timeout: failure #1, delay 0
    assert sm.failures == 1
    assert sm.tick(now=31.5)              # retries immediately (ladder[0])
    svc.process_all()
    assert sm.acked == 1
    assert sm.failures == 0               # ack resets the ladder


def test_nack_ladder_escalates_delays(env):
    svc, factory, d = boot(env)
    sm = d.make_summary_manager(SummaryConfig(
        max_ops=1, retry_delays=(0.0, 10.0, 60.0),
    ))
    text_of(d).insert_text(0, "x")
    map_of(d).set("k", 1)
    d.runtime.flush()
    svc.process_all()
    assert sm.tick(now=0.0)
    svc.process_all()
    assert sm.acked == 1
    doc = svc.document("doc")
    text_of(d).insert_text(0, "y")
    d.runtime.flush()
    svc.process_all()
    # Nack #1 (snapshot loss): immediate retry allowed (ladder[0] = 0).
    doc._snapshots.clear()
    assert sm.tick(now=100.0)
    svc.process_all()
    assert sm.failures == 1
    # Nack #2: uploads full blobs... but sabotage the upload table so the
    # scribe nacks again -> ladder[1] = 10s holds the next attempt.
    real_upload = doc.upload_summary
    doc.upload_summary = lambda tree_: "bogus-handle"
    assert sm.tick(now=100.5)
    svc.process_all()
    assert sm.failures == 2
    doc.upload_summary = real_upload
    assert not sm.tick(now=105.0)         # inside the 10s back-off
    assert sm.tick(now=110.6)             # ladder elapsed
    svc.process_all()
    assert sm.failures == 0 and sm.acked == 2


def test_stalled_summarizer_reelection_takeover(env):
    """The elected summarizer goes unresponsive; once reelection_ops ops
    pass without an acked summary, every replica deterministically elects
    the next client in join order, which summarizes without a missed
    window (ref summarizerClientElection.ts maxOpsSinceLastSummary)."""
    svc, factory, d = boot(env)
    c2 = load(factory, "second")
    svc.process_all()
    cfg = dict(max_ops=4, reelection_ops=8)
    sm1 = d.make_summary_manager(SummaryConfig(**cfg))
    sm2 = c2.make_summary_manager(SummaryConfig(**cfg))
    assert sm1.is_elected() and not sm2.is_elected()

    # sm1 stalls (never ticks). Ops accumulate past the re-election window.
    for i in range(9):
        text_of(c2).insert_text(0, "z")
        c2.runtime.flush()
        svc.process_all()
    assert not sm1.is_elected(), "stalled summarizer must lose election"
    assert sm2.is_elected()
    assert sm2.elected_summarizer() == "second"
    assert sm2.tick(now=0.0)
    svc.process_all()
    assert sm2.acked == 1
    # The ack resets the shared op counter: election returns to the ring
    # head on every replica.
    assert sm1.is_elected() and not sm2.is_elected()


# --------------------------------------------------------------------------
# Incremental forest summarization (ref incrementalSummarizationUtils.ts)
# --------------------------------------------------------------------------

def _tree_of(c):
    return c.runtime.datastore("root").get_channel("jsontree")


def _tree_summary_node(summary_tree):
    return summary_tree["entries"]["datastores"]["entries"]["root"][
        "entries"]["channels"]["entries"]["jsontree"]


def test_tree_incremental_summary_reuses_clean_chunks(env):
    """A 4-chunk tree document: after a deep edit to ONE subtree, the next
    summary re-uploads only that chunk; the other three ride handles — and
    a late joiner loads the materialized snapshot exactly."""
    from fluidframework_tpu.dds.tree.changeset import make_insert, make_set_value
    from fluidframework_tpu.dds.tree.schema import build_node, leaf

    svc, factory, d = boot(env, extra_channels=[("sharedTree", "jsontree")])
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))
    t = _tree_of(d)
    K = t.CHUNK_ROOTS
    for i in range(4 * K):  # 32 root subtrees = 4 chunks
        t.submit_change(make_insert([], "", i, [
            build_node("row", cells=[leaf(i), leaf(i * 10)])
        ]))
    d.runtime.flush()
    svc.process_all()
    assert sm.tick(now=0.0)
    svc.process_all()
    assert sm.acked == 1

    # Deep value edit inside chunk 2 only.
    t.submit_change(make_set_value([("", 2 * K + 3), ("cells", 1)], 777))
    d.runtime.flush()
    svc.process_all()
    node = _tree_summary_node(d.runtime.build_summary_tree())
    forest = node["entries"]["forest"]["entries"]
    kinds = {k: forest[k]["type"] for k in sorted(forest)}
    assert kinds == {"0": "handle", "1": "handle", "2": "blob", "3": "handle"}

    assert sm.tick(now=1.0)
    svc.process_all()
    assert sm.acked == 2

    # The scribe-materialized snapshot round-trips into a fresh client.
    late = load(factory, "late")
    svc.process_all()
    lt = _tree_of(late)
    assert [n.to_json() for n in lt.forest.root_field] == [
        n.to_json() for n in t.forest.root_field
    ]
    assert lt.forest.root_field[2 * K + 3].fields["cells"][1].value == 777


def test_tree_structural_change_dirties_suffix_chunks(env):
    from fluidframework_tpu.dds.tree.changeset import make_insert, make_remove
    from fluidframework_tpu.dds.tree.schema import leaf

    svc, factory, d = boot(env, extra_channels=[("sharedTree", "jsontree")])
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))
    t = _tree_of(d)
    K = t.CHUNK_ROOTS
    for i in range(3 * K):
        t.submit_change(make_insert([], "", i, [leaf(i)]))
    d.runtime.flush()
    svc.process_all()
    assert sm.tick(now=0.0)
    svc.process_all()

    # Remove in chunk 1: indices shift from there on -> chunks 1..2 dirty,
    # chunk 0 rides a handle.
    t.submit_change(make_remove([], "", K + 1, 1))
    d.runtime.flush()
    svc.process_all()
    node = _tree_summary_node(d.runtime.build_summary_tree())
    forest = node["entries"]["forest"]["entries"]
    kinds = {k: forest[k]["type"] for k in sorted(forest)}
    assert kinds == {"0": "handle", "1": "blob", "2": "blob"}
    assert sm.tick(now=1.0)
    svc.process_all()
    assert sm.acked == 2
    late = load(factory, "late2")
    svc.process_all()
    assert [n.value for n in _tree_of(late).forest.root_field] == [
        n.value for n in t.forest.root_field
    ]


def test_tree_remote_growth_never_dangles_chunk_handles(env):
    """A REMOTE append that grows the chunk domain past a chunk boundary
    must dirty the new tail chunk: the next summary may not reference a
    chunk the previous snapshot never had (review repro: pre-apply
    marking left chunk 2 clean and the scribe nacked the summary)."""
    from fluidframework_tpu.dds.tree.changeset import make_insert
    from fluidframework_tpu.dds.tree.schema import leaf

    svc, factory, d = boot(env, extra_channels=[("sharedTree", "jsontree")])
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))
    t = _tree_of(d)
    K = t.CHUNK_ROOTS
    for i in range(2 * K):
        t.submit_change(make_insert([], "", i, [leaf(i)]))
    d.runtime.flush()
    svc.process_all()
    assert sm.tick(now=0.0)
    svc.process_all()
    assert sm.acked == 1

    other = load(factory, "other")
    svc.process_all()
    ot = _tree_of(other)
    ot.submit_change(make_insert([], "", 2 * K, [leaf(999)]))  # new chunk 2
    other.runtime.flush()
    svc.process_all()

    assert sm.tick(now=1.0)
    svc.process_all()
    assert sm.failures == 0, "summary nacked: dangling chunk handle"
    assert sm.acked == 2
    late = load(factory, "late3")
    svc.process_all()
    assert [n.value for n in _tree_of(late).forest.root_field] == [
        n.value for n in t.forest.root_field
    ]


# --------------------------------------------------------------------------
# Hidden summarizer client (ref summaryManager.ts:95 + summarizer.ts:89)
# --------------------------------------------------------------------------

def test_hidden_summarizer_summarizes_despite_parent_pending_ops(env):
    """The elected interactive client spawns a hidden summarizer client;
    summaries flow even while the parent holds UNFLUSHED local edits (the
    exact property the reference spawns a separate client for), and the
    hidden client never appears in the election."""
    svc, factory, d = boot(env)
    hs = d.make_hidden_summarizer("doc", factory, SummaryConfig(max_ops=1))
    text_of(d).insert_text(0, "acked")
    d.runtime.flush()
    svc.process_all()
    assert hs.tick(now=0.0) is False  # spawns; hidden join still in flight
    svc.process_all()                 # hidden client joins
    # Parent now holds a PENDING (in-flight, unacked) local edit.
    text_of(d).insert_text(0, "pending-")
    d.runtime.flush()
    assert d.runtime.pending_op_count > 0
    # The parent itself REFUSES to summarize with pending ops...
    sm_direct = d.make_summary_manager(SummaryConfig(max_ops=1))
    assert sm_direct.tick(now=0.0) is False
    # ...but the hidden client has none and summarizes regardless.
    assert hs.tick(now=0.0)
    svc.process_all()
    assert hs.acked == 1
    # The summarize op came from the hidden identity...
    _, snap = svc.document("doc").latest_snapshot()
    assert snap["runtime"]["datastores"]["root"]["channels"]["text"] is not None
    assert any(
        cid.endswith("/summarizer") for cid in d.runtime.quorum_table
    )
    # ...which no replica's election ever counts.
    sm_watch = d.make_summary_manager(SummaryConfig(max_ops=1))
    assert sm_watch.elected_summarizer() == "creator"
    late = load(factory, "late-h")
    svc.process_all()
    assert text_of(late).text == text_of(d).text == "pending-acked"


def test_hidden_summarizer_closes_on_lost_election(env):
    svc, factory, d = boot(env)
    c2 = load(factory, "second")
    svc.process_all()
    hs = d.make_hidden_summarizer("doc", factory, SummaryConfig(max_ops=1))
    text_of(d).insert_text(0, "x")
    d.runtime.flush()
    svc.process_all()
    assert hs.tick(now=0.0) is False  # spawn; join in flight
    svc.process_all()
    assert hs.tick(now=0.0)
    svc.process_all()
    assert hs.acked == 1 and hs.summarizer is not None
    # The parent leaves: election moves to "second"; the hidden client
    # shuts down on the next tick and its leave sequences.
    d.disconnect()
    svc.process_all()
    assert not hs.parent_elected()
    assert hs.tick(now=1.0) is False
    assert hs.summarizer is None
    svc.process_all()
    assert not any(
        cid.endswith("/summarizer")
        for cid in c2.runtime.quorum_table
    )
    sm2 = c2.make_summary_manager(SummaryConfig(max_ops=1))
    assert sm2.is_elected()


def test_deep_spine_incremental_summary_single_root_array(env):
    """THE common app shape — one root array node holding the items: the
    chunk domain descends the spine, items chunk, deep value edits leave
    clean chunks riding handles, and late joiners load the spliced
    snapshot across generations."""
    from fluidframework_tpu.dds.tree import SchemaFactory, TreeViewConfiguration

    svc, factory, d = boot(env, extra_channels=[("sharedTree", "jsontree")])
    sm = d.make_summary_manager(SummaryConfig(max_ops=1))
    sf = SchemaFactory("ds")
    Cell = sf.object("Cell", v=sf.number)
    Cells = sf.array("Cells", Cell)
    t = _tree_of(d)
    view = t.typed_view(TreeViewConfiguration(Cells))
    view.initialize([Cell(v=i) for i in range(3 * t.CHUNK_ROOTS)])
    d.runtime.flush()
    svc.process_all()
    assert sm.tick(now=0.0)
    svc.process_all()
    assert sm.acked == 1

    view.root[2 * t.CHUNK_ROOTS + 1].v = 777  # dirty chunk 2 only
    d.runtime.flush()
    svc.process_all()
    node = _tree_summary_node(d.runtime.build_summary_tree())
    forest = node["entries"]["forest"]["entries"]
    kinds = {k: forest[k]["type"] for k in sorted(forest)}
    assert kinds == {"0": "handle", "1": "handle", "2": "blob"}
    assert sm.tick(now=1.0)
    svc.process_all()
    assert sm.acked == 2

    late = load(factory, "late-spine")
    svc.process_all()
    lv = _tree_of(late).typed_view(TreeViewConfiguration(Cells))
    vals = [c.v for c in lv.root]
    assert vals[2 * t.CHUNK_ROOTS + 1] == 777 and vals[5] == 5


def test_reserved_summarizer_suffix_rejected(env):
    svc, factory, d = boot(env)
    with pytest.raises(ValueError, match="reserved"):
        load(factory, "sneaky/summarizer")


def test_parent_close_stops_hidden_summarizer(env):
    svc, factory, d = boot(env)
    hs = d.make_hidden_summarizer("doc", factory, SummaryConfig(max_ops=1))
    text_of(d).insert_text(0, "x")
    d.runtime.flush()
    svc.process_all()
    hs.tick(now=0.0)
    svc.process_all()
    hs.tick(now=0.0)
    svc.process_all()
    assert hs.summarizer is not None
    d.close()  # parent lifecycle tears the hidden client down too
    assert hs.summarizer is None
    svc.process_all()
    assert not any(
        cid.endswith("/summarizer")
        for cid in svc.document("doc").sequencer.clients()
    )
