"""Fault-isolated, checkpointed recovery for the batched engines.

Pins the robustness contract of models/doc_batch_engine +
server/ordered_log.CheckpointStore:

- a malformed sequenced op in ONE doc of a batched step never perturbs the
  other docs (byte-identical to a no-fault run) — the poisoned doc is
  quarantined, stays serviceable, and recovers with replay bounded by the
  checkpoint interval, then re-admits to the device batch;
- an engine crash restarts from the durable checkpoint records and
  converges to the same state as an uninterrupted run, skipping the
  already-checkpointed prefix of the replayed stream;
- capacity (grow-lane) recovery replays the checkpoint TAIL, not the full
  op history ("Unbounded by design for now" is retired);
- the divergence watchdog quarantines a doc whose device state stops
  matching the host-oracle replay;
- TreeBatchEngine restarts from its forest + EditManager records.
"""

from __future__ import annotations

import tempfile

import numpy as np
import pytest

from fluidframework_tpu.models.doc_batch_engine import DocBatchEngine
from fluidframework_tpu.models.tree_batch_engine import TreeBatchEngine
from fluidframework_tpu.ops import mergetree_kernel as mk
from fluidframework_tpu.protocol.messages import MessageType, SequencedMessage
from fluidframework_tpu.server.ordered_log import CheckpointStore


# ------------------------------------------------------------------ helpers

def _join(client: str, short: int) -> SequencedMessage:
    return SequencedMessage(
        seq=0, min_seq=0, ref_seq=0, client_id=client, client_seq=0,
        type=MessageType.JOIN, contents={"clientId": client, "short": short},
    )


def _op(seq: int, contents: dict, client: str = "w0", ref: int = 0) -> SequencedMessage:
    return SequencedMessage(
        seq=seq, min_seq=0, ref_seq=ref, client_id=client, client_seq=seq,
        type=MessageType.OP, contents=contents,
    )


def _ins(seq: int, pos: int, text: str, **kw) -> SequencedMessage:
    return _op(seq, {"type": 0, "pos1": pos, "seg": text}, **kw)


def _rm(seq: int, pos1: int, pos2: int, **kw) -> SequencedMessage:
    return _op(seq, {"type": 1, "pos1": pos1, "pos2": pos2}, **kw)


def _schedule(n_docs: int, rounds: int, seed: int = 0, poison: tuple | None = None):
    """A deterministic per-doc op schedule (single writer, valid in its own
    perspective); returns [(doc, msg, is_poison)] in per-doc sequence
    order.  ``poison=(doc, round)`` splices ONE malformed insert into that
    doc's stream occupying a real sequence number (as a sequencer would
    assign it), shifting the doc's later seqs — so the control run feeds
    the same schedule minus the poison op with identical numbering."""
    rng = np.random.default_rng(seed)
    out: list[tuple[int, SequencedMessage, bool]] = []
    lengths = [0] * n_docs
    seqs = [0] * n_docs
    for r in range(rounds):
        for d in range(n_docs):
            if poison == (d, r):
                seqs[d] += 1
                out.append((d, _ins(seqs[d], 10**6, "XX"), True))
            seqs[d] += 1
            if lengths[d] >= 4 and rng.random() < 0.3:
                p = int(rng.integers(0, lengths[d] - 1))
                out.append((d, _rm(seqs[d], p, p + 1), False))
                lengths[d] -= 1
            else:
                p = int(rng.integers(0, lengths[d] + 1))
                out.append((d, _ins(seqs[d], p, "ab"), False))
                lengths[d] += 2
    return out


def _mk_engine(n_docs: int, store=None, **kw) -> DocBatchEngine:
    return DocBatchEngine(
        n_docs, max_insert_len=8, ops_per_step=4, use_mesh=False,
        checkpoint_store=store, **kw,
    )


# --------------------------------------------------------------- quarantine

def test_malformed_op_isolated_and_recovered_with_bounded_replay():
    """1 poisoned doc of 8: the other 7 stay byte-identical to a no-fault
    run; the poisoned doc quarantines, drops exactly the poison op, and
    its recovery replay is bounded by the checkpoint interval."""
    D, ROUNDS, CKPT = 8, 12, 4
    sched = _schedule(D, ROUNDS, poison=(3, (2 * ROUNDS) // 3))
    total_ops = ROUNDS  # per doc (poison excluded)

    # Control: the same stream minus the poison op (identical seqs).
    ctl = _mk_engine(D)
    for d in range(D):
        ctl.ingest(d, _join("w0", 0))
    for d, m, is_poison in sched:
        if not is_poison:
            ctl.ingest(d, m)
    ctl.step()
    assert not ctl.errors().any()
    expected = [ctl.text(d) for d in range(D)]

    # Faulted run with checkpoints.
    store = CheckpointStore(tempfile.mkdtemp())
    eng = _mk_engine(D, store, checkpoint_every=CKPT)
    for d in range(D):
        eng.ingest(d, _join("w0", 0))
    seen = [0] * D
    for d, m, _is_poison in sched:
        seen[d] += 1
        eng.ingest(d, m)
        if seen[d] % CKPT == 0:
            eng.step()  # step cadence drives the checkpoint cadence
    eng.step()

    # Isolation: every healthy doc byte-identical to the no-fault run.
    for d in range(D):
        if d != 3:
            assert eng.text(d) == expected[d], f"doc {d} perturbed by doc 3"
    # The poisoned doc was quarantined, dropped the poison op, and
    # otherwise converged to the no-fault state.
    assert 3 in eng.quarantine
    h = eng.health()
    assert h["quarantines"] == 1 and h["poison_ops_dropped"] >= 1
    assert eng.text(3) == expected[3]
    # Bounded recovery: the quarantine replay consumed the checkpoint TAIL,
    # strictly less than the doc's full history.
    assert 0 < h["quarantine_replay_len"] < total_ops
    assert h["checkpoints_written"] > 0

    # Serviceable while quarantined: reads + validated op application.
    n = len(eng.text(3))
    eng.ingest(3, _ins(2000, 0, "zz"))
    assert eng.text(3) == "zz" + expected[3] and len(eng.text(3)) == n + 2

    # Clean replay -> readmission to the lockstep batch.
    assert eng.readmit(3)
    assert 3 not in eng.quarantine
    eng.ingest(3, _ins(2001, 0, "qq"))
    eng.step()
    assert eng.text(3) == "qqzz" + expected[3]
    assert not eng.errors().any()


def test_decode_failure_quarantines_at_ingest():
    """An op that cannot even be decoded (unknown client) quarantines the
    doc at ingest time; siblings are untouched."""
    eng = _mk_engine(2)
    for d in range(2):
        eng.ingest(d, _join("w0", 0))
        eng.ingest(d, _ins(1, 0, "hi"))
    eng.ingest(0, _ins(2, 0, "xx", client="ghost"))  # not in quorum
    eng.step()
    assert 0 in eng.quarantine and 1 not in eng.quarantine
    assert eng.text(0) == "hi" and eng.text(1) == "hi"
    assert eng.health()["poison_ops_dropped"] >= 1
    # Legal-but-unsupported wire forms (dict/list insert specs) are a
    # feature gap, not poison: they fail LOUD instead of quarantine-
    # dropping into a silent split-brain, and leave the doc healthy.
    with pytest.raises(NotImplementedError):
        eng.ingest(1, _op(2, {"type": 0, "pos1": 0, "seg": {"text": "x"}}))
    eng.ingest(1, _ins(2, 2, "!"))
    eng.step()
    assert 1 not in eng.quarantine and eng.text(1) == "hi!"


def test_watchdog_quarantines_diverged_doc():
    """A corrupted device row (simulated bit-rot) is caught by the sampling
    watchdog and the doc moves to the (authoritative) oracle lane."""
    import jax.numpy as jnp

    eng = _mk_engine(2, watchdog_every=1)
    for d in range(2):
        eng.ingest(d, _join("w0", 0))
        eng.ingest(d, _ins(1, 0, "hello"))
    eng.step()
    assert not eng.quarantine
    # Flip a codepoint in doc 0's text pool behind the engine's back.
    bad = eng.state.text.at[0, 0].set(ord("X"))
    eng.state = eng.state._replace(text=bad)
    eng.ingest(0, _ins(2, 0, "a"))
    eng.ingest(1, _ins(2, 0, "a"))
    eng.step()
    assert 0 in eng.quarantine
    assert eng.health()["watchdog_mismatches"] == 1
    assert eng.text(0) == "ahello"  # oracle state, corruption discarded


# ----------------------------------------------- readmission policy / budget

def test_auto_readmit_after_backoff():
    """A quarantined doc re-enters the lockstep batch automatically after
    the backoff window — no operator readmit() call."""
    eng = _mk_engine(2, readmit_after_steps=2)
    for d in range(2):
        eng.ingest(d, _join("w0", 0))
        eng.ingest(d, _ins(1, 0, "hi"))
    eng.step()
    eng.ingest(0, _ins(2, 10**6, "XX"))  # poison
    eng.step()
    assert 0 in eng.quarantine
    assert eng.health()["readmits_scheduled"] == 1
    for s in range(3, 8):  # idle-ish steps advance the readmit clock
        eng.ingest(1, _ins(s, 0, "a"))
        eng.step()
        if 0 not in eng.quarantine:
            break
    h = eng.health()
    assert 0 not in eng.quarantine and h["auto_readmissions"] == 1
    assert h["quarantine_flaps"] == 1 and h["readmits_scheduled"] == 0
    # The readmitted doc keeps applying on the device path.
    eng.ingest(0, _ins(3, 0, "ok"))
    eng.step()
    assert eng.text(0).startswith("ok")
    assert not eng.errors().any()


def test_poison_budget_routes_flapping_doc_to_oracle():
    """A doc that keeps getting re-poisoned after clean readmissions burns
    its poison budget and is permanently oracle-routed (still serviceable,
    never auto-readmitted again)."""
    eng = _mk_engine(1, readmit_after_steps=1, poison_budget=2)
    eng.ingest(0, _join("w0", 0))
    eng.ingest(0, _ins(1, 0, "hi"))
    eng.step()
    seq = 2
    for _flap in range(4):
        eng.ingest(0, _ins(seq, 10**6, "XX"))
        seq += 1
        eng.step()
        for _ in range(6):
            eng.step()
            if 0 not in eng.quarantine:
                break
        if 0 in eng.oracles:
            break
    h = eng.health()
    assert 0 in eng.oracles and 0 not in eng.quarantine
    assert h["poison_routed_docs"] == 1 and h["quarantine_flaps"] == 3
    # Still serviceable through the oracle lane.
    eng.ingest(0, _ins(seq, 0, "zz"))
    assert eng.text(0).startswith("zz")


def test_watchdog_digest_prefilter_skips_unchanged_docs():
    """The device-side text-pool digest gates the host-replay watchdog: an
    idle doc verified once is skipped until its digest drifts, while real
    divergence (bit-rot) still quarantines."""
    eng = _mk_engine(2, watchdog_every=1)
    for d in range(2):
        eng.ingest(d, _join("w0", 0))
        eng.ingest(d, _ins(1, 0, "hello"))
    eng.step()  # both docs verified, digests pinned
    checks0 = eng.health()["watchdog_checks"]
    eng.ingest(0, _ins(2, 0, "a"))  # only doc 0 moves
    eng.step()
    h = eng.health()
    assert h["watchdog_prefiltered"] >= 1  # doc 1 skipped, digest unmoved
    assert h["watchdog_checks"] == checks0 + 1
    # Divergence still caught: corrupt doc 0's pool behind the engine.
    bad = eng.state.text.at[0, 0].set(ord("X"))
    eng.state = eng.state._replace(text=bad)
    eng.ingest(0, _ins(3, 0, "b"))
    eng.ingest(1, _ins(2, 5, "!"))
    eng.step()
    assert 0 in eng.quarantine
    assert eng.health()["watchdog_mismatches"] == 1


# ------------------------------------------------------------ crash/restart

def test_engine_restart_restores_from_durable_checkpoint():
    """Simulated crash: a fresh engine restores every doc from the durable
    records and — fed the FULL stream from offset 0, as a restarted
    consumer would — skips the checkpointed prefix and converges to the
    uninterrupted run's state."""
    D, ROUNDS = 4, 10
    sched = _schedule(D, ROUNDS, seed=5)
    tmp = tempfile.mkdtemp()
    store = CheckpointStore(tmp)
    eng = _mk_engine(D, store, checkpoint_every=3)
    for d in range(D):
        eng.ingest(d, _join("w0", 0))
    for i, (d, m, _p) in enumerate(sched):
        eng.ingest(d, m)
        if i % 5 == 4:
            eng.step()
    eng.step()
    eng.maybe_checkpoint(force=True)
    expected = [eng.text(d) for d in range(D)]
    del eng  # crash

    eng2 = _mk_engine(D, CheckpointStore(tmp), checkpoint_every=3)
    restored = eng2.restore_from_checkpoints()
    assert restored == list(range(D))
    # Checkpoint state alone already reproduces the pre-crash state.
    assert [eng2.text(d) for d in range(D)] == expected
    # Full-stream replay (offset 0) is idempotent: the checkpointed prefix
    # is skipped, nothing double-applies.
    for d in range(D):
        eng2.ingest(d, _join("w0", 0))
    for d, m, _p in sched:
        eng2.ingest(d, m)
    eng2.step()
    assert [eng2.text(d) for d in range(D)] == expected
    assert eng2.health()["checkpointed_ops_skipped"] == D * ROUNDS
    assert not eng2.errors().any()


def test_restart_then_new_ops_converge():
    """Restore + genuinely new ops after the checkpoint seq apply once."""
    tmp = tempfile.mkdtemp()
    store = CheckpointStore(tmp)
    eng = _mk_engine(1, store, checkpoint_every=1)
    eng.ingest(0, _join("w0", 0))
    eng.ingest(0, _ins(1, 0, "base"))
    eng.step()  # checkpoint at seq 1
    assert eng.health()["checkpoints_written"] == 1

    eng2 = _mk_engine(1, CheckpointStore(tmp))
    assert eng2.restore_from_checkpoints() == [0]
    eng2.ingest(0, _join("w0", 0))
    eng2.ingest(0, _ins(1, 0, "base"))   # replayed: skipped
    eng2.ingest(0, _ins(2, 4, "!"))      # new
    eng2.step()
    assert eng2.text(0) == "base!"


# --------------------------------------------------- bounded grow recovery

def test_grow_recovery_replays_checkpoint_tail_not_full_history():
    """Capacity overflow AFTER a checkpoint replays base + tail: the
    recovery_replay_len gauge stays strictly below the op history."""
    store = CheckpointStore(tempfile.mkdtemp())
    eng = DocBatchEngine(
        1, max_segments=6, max_insert_len=8, ops_per_step=4, use_mesh=False,
        checkpoint_store=store, checkpoint_every=4,
    )
    eng.ingest(0, _join("w0", 0))
    # Phase 1: 4 front-inserts -> 4 segments, fits, checkpointed.
    for s in range(1, 5):
        eng.ingest(0, _ins(s, 0, "ab"))
    eng.step()
    assert eng.health()["checkpoints_written"] == 1
    assert not eng.errors().any()
    # Phase 2: 4 more -> 8 segments > 6 latches ERR_SEG_OVERFLOW; the grow
    # lane replays checkpoint(4 segs) + 4-op tail, not all 8 ops.
    for s in range(5, 9):
        eng.ingest(0, _ins(s, 0, "ab"))
    eng.step()
    assert 0 in eng.overflow
    assert not eng.errors().any()
    assert eng.text(0) == "ab" * 8
    h = eng.health()
    assert 0 < h["recovery_replay_len"] <= 4 < 8
    assert h["capacity_recoveries"] == 1


# ------------------------------------------------------------- tree engine

def test_tree_engine_restart_restores_from_checkpoint():
    """TreeBatchEngine crash/restart: forest + EditManager records restore
    the host state, the device columns re-materialize, and a full-stream
    replay is skipped up to the checkpoint seq."""
    from test_tree_batch_engine import drive_tree_docs

    svc, expected = drive_tree_docs(3, seed=2, steps=20)
    tmp = tempfile.mkdtemp()
    eng = TreeBatchEngine(
        3, checkpoint_store=CheckpointStore(tmp), checkpoint_every=8,
    )
    for d in range(3):
        for msg in svc.document(f"doc{d}").sequencer.log:
            eng.ingest(d, msg)
    eng.step()
    eng.maybe_checkpoint(force=True)
    assert eng.health()["checkpoints_written"] >= 3
    for d in range(3):
        assert eng.values(d) == expected[d]
    del eng  # crash

    eng2 = TreeBatchEngine(3, checkpoint_store=CheckpointStore(tmp))
    assert eng2.restore_from_checkpoints() == [0, 1, 2]
    eng2.step()  # apply the re-materialization rows
    for d in range(3):
        assert eng2.values(d) == expected[d], f"doc {d} restore diverged"
    # Replaying the full stream from offset 0 double-applies nothing.
    for d in range(3):
        for msg in svc.document(f"doc{d}").sequencer.log:
            eng2.ingest(d, msg)
    eng2.step()
    for d in range(3):
        assert eng2.values(d) == expected[d], f"doc {d} replay diverged"
    assert eng2.health()["checkpointed_ops_skipped"] > 0


def test_checkpoint_store_survives_torn_write():
    """A torn/corrupt record never blocks restart: load() degrades to None
    (full replay) instead of raising."""
    import os

    tmp = tempfile.mkdtemp()
    store = CheckpointStore(tmp)
    store.save("doc0", 7, {"engine": "doc_batch", "x": 1})
    assert store.load("doc0")["seq"] == 7
    path = store._path("doc0")
    with open(path, "w") as f:
        f.write('{"truncated')
    assert store.load("doc0") is None
    # docs() decodes ids from FILENAMES (the O(entries) restore scan), so
    # the torn record still lists — and still never blocks restart: its
    # load() degrades to None and the restore skips it.
    assert store.docs() == ["doc0"]
    # And the tmp-file discipline: no stray .tmp left behind.
    store.save("doc0", 9, {"engine": "doc_batch"})
    assert store.load("doc0")["seq"] == 9
    assert not [p for p in os.listdir(os.path.dirname(path)) if p.endswith(".tmp")]
