"""Per-backend conformance fuzz: every registered dispatch plane vs the
lax oracle.

The dispatch seam (models/dispatch.py) makes backends pluggable; THIS
harness is what makes them cheap to add.  It parameterizes over the
registered planes — today the default jax/XLA plane (parallel.mesh) and
the native CPU plane (parallel.native_plane + native/megastep.cpp) — and
pins, for each:

- **byte identity with the lax oracle** over seeded multi-writer traces
  spanning the full op palette (inserts incl. multi-chunk/tie-break and
  splits, removes, annotates, sided obliterates with insert-time
  swallow, acks of pending stamps, zamboni compaction), compared on the
  FULL raw state columns — padding remnants included — plus the per-doc
  error latch (capacity/poison bits must latch identically);
- **engine-level equivalence**: a DocBatchEngine serving on the plane
  produces the same texts/annotations/digests as one on the oracle
  plane, through the real ingest -> staging -> megastep -> recover path;
- **backend-invariant checkpoints**: a checkpoint written by an engine
  on one backend restores on the other (both directions).

Tier-1 runs a short sweep; ``-m slow`` runs the 6-seed deep sweep.
New planes (GPU, Pallas) land by adding one entry to ``PLANES``.
"""

from __future__ import annotations

import importlib
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluidframework_tpu.models import dispatch
from fluidframework_tpu.models.doc_batch_engine import (
    DocBatchEngine,
    _fleet_compact_body,
    _fleet_digest,
)
from fluidframework_tpu.ops import mergetree_kernel as mk
from fluidframework_tpu.protocol.stamps import LOCAL_BASE
from fluidframework_tpu.server.ordered_log import CheckpointStore

from test_engine_checkpoint import _ins, _join
from test_megastep import _schedule

PLANES = [
    pytest.param("fluidframework_tpu.parallel.mesh", id="jax"),
    pytest.param("fluidframework_tpu.parallel.native_plane", id="native"),
]


def _restore_default_plane():
    mesh_mod = importlib.import_module("fluidframework_tpu.parallel.mesh")
    dispatch.register_dispatch_plane(mesh_mod)


@pytest.fixture(params=PLANES)
def plane(request):
    """Import + register the plane under test; ALWAYS hand the registry
    back to the default jax plane afterwards (registration is last-wins
    process state — leaking the native plane would silently re-backend
    every engine constructed by later test modules)."""
    mod = importlib.import_module(request.param)
    dispatch.register_dispatch_plane(mod)
    try:
        yield mod
    finally:
        _restore_default_plane()


# ----------------------------------------------------------- trace maker

def make_trace(seed, D, K, B, L, n_rings, chunky=True):
    """Seeded multi-writer [K, D, B] op rings across the full palette:
    inserts (some deliberately out of range), multi-chunk same-stamp
    inserts (tie-break path), removes, annotates (incl. out-of-range
    prop slots), sided obliterates, pending local inserts + later acks.
    Positions are approximate on purpose — poison ops latch error bits,
    and the latch itself is part of the conformance surface."""
    rng = np.random.default_rng(seed)
    lengths = [0] * D
    seqs = [0] * D
    local = [0] * D
    rings = []
    for _ in range(n_rings):
        ops = np.zeros((K, D, B, 8), np.int32)
        pays = np.zeros((K, D, B, L), np.int32)
        for k in range(K):
            for d in range(D):
                b = 0
                while b < B:
                    roll = rng.random()
                    seqs[d] += 1
                    key = seqs[d]
                    client = int(rng.integers(0, 4))
                    ref = max(0, seqs[d] - int(rng.integers(1, 6)))
                    ln = lengths[d]
                    if chunky and roll < 0.15 and b + 3 <= B:
                        pos = int(rng.integers(0, ln + 1))
                        for _c in range(3):
                            tl = int(rng.integers(1, L + 1))
                            ops[k, d, b] = [1, key, client, ref, pos, 0, tl, 0]
                            pays[k, d, b, :tl] = rng.integers(65, 91, tl)
                            lengths[d] += tl
                            b += 1
                        continue
                    if roll < 0.4 or ln < 4:
                        tl = int(rng.integers(1, L + 1))
                        pos = int(rng.integers(0, ln + 2))
                        ops[k, d, b] = [1, key, client, ref, pos, 0, tl, 0]
                        pays[k, d, b, :tl] = rng.integers(65, 91, tl)
                        lengths[d] += tl
                    elif roll < 0.55:
                        p1 = int(rng.integers(0, ln))
                        p2 = int(rng.integers(p1, ln + 1))
                        ops[k, d, b] = [2, key, client, ref, p1, p2, 0, 0]
                    elif roll < 0.68:
                        p1 = int(rng.integers(0, ln))
                        p2 = int(rng.integers(p1, ln + 1))
                        ops[k, d, b] = [
                            3, key, client, ref, p1, p2,
                            int(rng.integers(0, 5)), int(rng.integers(1, 100)),
                        ]
                    elif roll < 0.82:
                        p1 = int(rng.integers(0, max(1, ln)))
                        p2 = int(rng.integers(p1, max(p1 + 1, ln)))
                        ops[k, d, b] = [
                            5, key, client, ref, p1, p2,
                            int(rng.integers(0, 2)), int(rng.integers(0, 2)),
                        ]
                    elif roll < 0.92:
                        local[d] += 1
                        ops[k, d, b] = [
                            1, LOCAL_BASE + local[d], -2, ref,
                            int(rng.integers(0, ln + 1)), 0, 2, 0,
                        ]
                        pays[k, d, b, :2] = [97, 98]
                        lengths[d] += 2
                    else:
                        ls = (
                            int(rng.integers(1, local[d] + 1))
                            if local[d] else 0
                        )
                        ops[k, d, b] = [
                            4, key, int(rng.integers(0, 4)),
                            int(rng.integers(0, seqs[d] + 1)), 0, 0, ls, key,
                        ]
                    b += 1
        rings.append((ops, pays))
    return rings, seqs


def _assert_leaves_equal(a, b, tag):
    """Full-array byte identity — stricter than canonical_doc (shift
    remnants in padding slots must match too; the native kernel's
    high-water bound claims exact equivalence, so hold it to that)."""
    for name in mk.DocState._fields:
        xs, ys = getattr(a, name), getattr(b, name)
        xs = xs if isinstance(xs, tuple) else (xs,)
        ys = ys if isinstance(ys, tuple) else (ys,)
        for j, (x, y) in enumerate(zip(xs, ys)):
            assert np.array_equal(np.asarray(x), np.asarray(y)), (
                f"{tag}: field {name}[{j}] diverged"
            )


def _run_conformance(plane_mod, seed, D=8, K=3, B=8, L=6, S=32, T=256,
                     n_rings=4):
    """Replay one trace through the plane's fleet programs and through
    the single-device lax oracle; byte-compare after every ring AND
    after every compact."""
    proto = mk.init_state(S, 3, 2, T, 4)
    fleet = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (D,) + x.shape), proto
    )
    mesh = plane_mod.doc_mesh()
    da = plane_mod.fleet_doc_axes(mesh)
    s_plane = plane_mod.shard_fleet_state(fleet, mesh)
    specs = plane_mod.fleet_state_specs(s_plane, da)
    mega = plane_mod.mesh_fleet_program(
        mk.apply_megastep, mesh, specs,
        arg_specs=(plane_mod.P(None, da), plane_mod.P(None, da)),
    )
    compact = plane_mod.mesh_fleet_program(
        _fleet_compact_body, mesh, specs, arg_specs=(plane_mod.P(da),),
    )
    oracle_mega = jax.jit(mk.apply_megastep)
    oracle_compact = jax.jit(_fleet_compact_body)

    rings, seqs = make_trace(seed, D, K, B, L, n_rings)
    s_oracle = fleet
    for i, (ops, pays) in enumerate(rings):
        s_plane = mega(s_plane, jnp.asarray(ops), jnp.asarray(pays))
        s_oracle = oracle_mega(s_oracle, jnp.asarray(ops), jnp.asarray(pays))
        _assert_leaves_equal(s_oracle, s_plane, f"seed {seed} ring {i}")
        mins = np.array(
            [max(0, s - 7 - i) for s in seqs], np.int32
        )
        s_plane = compact(s_plane, jnp.asarray(mins))
        s_oracle = oracle_compact(s_oracle, jnp.asarray(mins))
        _assert_leaves_equal(
            s_oracle, s_plane, f"seed {seed} ring {i} post-compact"
        )
    # The error latch is part of the identity surface — and the trace
    # must actually have latched something, or the latch leg proved
    # nothing.
    assert int(plane_mod.error_count(s_plane.error)) == int(
        np.count_nonzero(np.asarray(s_oracle.error))
    )
    return np.asarray(s_oracle.error)


# --------------------------------------------------- program conformance

@pytest.mark.parametrize("seed", [0, 1])
def test_megastep_conformance_short(plane, seed):
    errs = _run_conformance(plane, seed)
    assert errs.any(), "trace never latched an error bit (weak trace)"


@pytest.mark.slow
@pytest.mark.parametrize("seed", [2, 3, 4, 5, 6, 7])
def test_megastep_conformance_deep(plane, seed):
    _run_conformance(plane, seed, D=8, K=4, B=12, L=8, S=64, T=1024,
                     n_rings=8)


# ---------------------------------------------------- engine conformance

def _run_engine(n_docs, sched, step_every=17):
    eng = DocBatchEngine(
        n_docs, remove_slots=4, max_insert_len=8, ops_per_step=4,
        use_mesh=True, megastep_k=4, max_segments=128, text_capacity=1024,
    )
    for d in range(n_docs):
        eng.ingest(d, _join("w0", 0))
    for i, (d, msg) in enumerate(sched):
        eng.ingest(d, msg)
        if (i + 1) % step_every == 0:
            eng.step()
    eng.step()
    return eng


def test_engine_on_plane_matches_oracle_plane(plane):
    """The whole serving path — ingest, staging ring, megastep dispatch,
    error readback, compaction — on the plane under test, vs the same
    schedule on the default plane."""
    D = 8
    sched = _schedule(D, 16, seed=11, obliterate=True)
    eng = _run_engine(D, sched)
    texts = [eng.text(d) for d in range(D)]
    annos = [eng.annotations(d) for d in range(D)]
    digest = np.asarray(_fleet_digest(eng.state)).tobytes()
    assert not eng.errors().any()

    _restore_default_plane()
    ref = _run_engine(D, sched)
    assert [ref.text(d) for d in range(D)] == texts
    assert [ref.annotations(d) for d in range(D)] == annos
    assert np.asarray(_fleet_digest(ref.state)).tobytes() == digest


# ------------------------------------------------ cross-backend restore

@pytest.mark.parametrize(
    "writer_plane,reader_plane",
    [
        ("fluidframework_tpu.parallel.native_plane",
         "fluidframework_tpu.parallel.mesh"),
        ("fluidframework_tpu.parallel.mesh",
         "fluidframework_tpu.parallel.native_plane"),
    ],
    ids=["native-to-jax", "jax-to-native"],
)
def test_checkpoint_round_trip_across_backends(writer_plane, reader_plane):
    """Checkpoints are backend-invariant: state crosses the native seam
    as the same arrays summary_to_state builds, so a checkpoint written
    under one plane restores byte-for-byte under the other."""
    D = 8
    sched = _schedule(D, 10, seed=12)
    tmp = tempfile.mkdtemp()
    try:
        dispatch.register_dispatch_plane(
            importlib.import_module(writer_plane)
        )
        store = CheckpointStore(tmp)
        eng = DocBatchEngine(
            D, max_insert_len=8, ops_per_step=4, use_mesh=True,
            megastep_k=4, max_segments=128, text_capacity=1024,
            checkpoint_store=store, checkpoint_every=3,
        )
        for d in range(D):
            eng.ingest(d, _join("w0", 0))
        for i, (d, m) in enumerate(sched):
            eng.ingest(d, m)
            if i % 5 == 4:
                eng.step()
        eng.step()
        eng.maybe_checkpoint(force=True)
        expected = [eng.text(d) for d in range(D)]
        assert not eng.errors().any()
        del eng

        dispatch.register_dispatch_plane(
            importlib.import_module(reader_plane)
        )
        eng2 = DocBatchEngine(
            D, max_insert_len=8, ops_per_step=4, use_mesh=True,
            megastep_k=4, max_segments=128, text_capacity=1024,
            checkpoint_store=CheckpointStore(tmp),
        )
        assert sorted(eng2.restore_from_checkpoints()) == list(range(D))
        assert [eng2.text(d) for d in range(D)] == expected
        # Replaying the full stream on the OTHER backend stays idempotent
        # and converges.
        for d in range(D):
            eng2.ingest(d, _join("w0", 0))
        for d, m in sched:
            eng2.ingest(d, m)
        eng2.step()
        assert [eng2.text(d) for d in range(D)] == expected
        assert not eng2.errors().any()
    finally:
        _restore_default_plane()


# --------------------------------------------- seg-lane loud degradation

def test_native_plane_seg_lanes_fall_back_loudly():
    """The native plane has no segment-parallel programs: an engine asked
    for seg_shards > 1 must NOT crash and must NOT silently pretend — it
    downgrades to doc-sharded serving and counts the downgrade."""
    try:
        dispatch.register_dispatch_plane(
            importlib.import_module("fluidframework_tpu.parallel.native_plane")
        )
        eng = DocBatchEngine(
            8, max_insert_len=8, ops_per_step=4, use_mesh=True,
            seg_shards=2, max_segments=64, text_capacity=512,
        )
        assert eng.seg_shards == 1
        assert eng._seg_megastep is None
        assert eng.health()["seg_plane_unsupported"] == 1
        assert eng.enable_segment_sharding(0) is False
        eng.ingest(0, _join("w0", 0))
        eng.ingest(0, _ins(1, 0, "ab"))
        eng.step()
        assert eng.text(0) == "ab"
    finally:
        _restore_default_plane()
