"""Wire-bytes -> device through the product stack (VERDICT r3 weak #4).

Writers edit through the normal sequenced path; a FleetConsumer subscribes
to the netserver firehose over REAL TCP sockets and feeds the raw bytes into
a DocBatchEngine via the C++ encoder — no per-op Python on the data plane.
The device fleet must reproduce every writer's converged text exactly.
"""

from __future__ import annotations

import random
import threading

import pytest

from fluidframework_tpu.dds.shared_string import SharedString
from fluidframework_tpu.models.doc_batch_engine import DocBatchEngine
from fluidframework_tpu.native.ingest_native import available
from fluidframework_tpu.server.fleet_consumer import FleetConsumer
from fluidframework_tpu.server.netserver import NetworkServer

pytestmark = pytest.mark.skipif(
    not available(), reason="native ingest encoder unavailable"
)


@pytest.fixture
def server():
    srv = NetworkServer().start()
    yield srv
    srv.stop()


def _writers(server, doc_id: str, n: int) -> list[SharedString]:
    with server.lock:
        doc = server.service.document(doc_id)
        out = []
        for w in range(n):
            c = SharedString(client_id=f"{doc_id}-w{w}")
            doc.connect(c.client_id, c.process)
            out.append(c)
        doc.process_all()
    return out


def _flush(server, doc_id: str, writers) -> int:
    """Submit outboxes; returns op messages sequenced."""
    n = 0
    with server.lock:
        doc = server.service.document(doc_id)
        for c in writers:
            for m in c.take_outbox():
                doc.submit(m)
                n += 1
        doc.process_all()
    return n


def test_wire_to_device_single_doc(server):
    writers = _writers(server, "d0", 2)
    a, b = writers
    a.insert_text(0, "hello")
    rows = _flush(server, "d0", writers)
    b.insert_text(5, " world")
    a.annotate_range(0, 5, 3, 7)
    rows += _flush(server, "d0", writers)
    a.remove_range(0, 1)
    rows += _flush(server, "d0", writers)

    eng = DocBatchEngine(1, max_segments=256, text_capacity=4096,
                         max_insert_len=8, ops_per_step=8, use_mesh=False,
                         recovery="off")
    fc = FleetConsumer("127.0.0.1", server.port, eng, ["d0"])
    try:
        fc.run_for(rows)
        assert eng.text(0) == a.text == "ello world"
        assert not eng.errors().any()
        # The data plane really was the native path.
        assert eng.hosts[0].mode == "native"
        assert fc.bytes_consumed > 0
    finally:
        fc.close()


def test_wire_to_device_fleet_with_live_tail(server):
    """Multi-doc fleet: catch-up history + live ops arriving while the
    consumer is attached, randomized edits, all docs converge."""
    rng = random.Random(3)
    n_docs = 4
    fleets = [(f"d{i}", _writers(server, f"d{i}", 2)) for i in range(n_docs)]
    rows = [0] * n_docs

    def edit_round():
        for i, (doc_id, writers) in enumerate(fleets):
            for c in writers:
                n = len(c.text)
                if rng.random() < 0.7 or n < 4:
                    c.insert_text(rng.randint(0, n), "".join(
                        rng.choice("abcdef") for _ in range(rng.randint(1, 6))
                    ))
                else:
                    p = rng.randint(0, n - 2)
                    c.remove_range(p, p + 1)
            rows[i] += _flush(server, doc_id, writers)

    for _ in range(4):
        edit_round()  # pre-attach history (exercises firehose catch-up)

    eng = DocBatchEngine(n_docs, max_segments=512, text_capacity=8192,
                         max_insert_len=8, ops_per_step=8, use_mesh=False,
                         recovery="off")
    fc = FleetConsumer("127.0.0.1", server.port, eng,
                       [d for d, _ in fleets])
    try:
        # Live tail lands while attached — from another thread, like a real
        # front-end serving concurrent writers.
        t = threading.Thread(target=lambda: [edit_round() for _ in range(3)])
        t.start()
        t.join()
        # Inserts of len<=8 are single rows; removes are single rows.
        fc.run_for(sum(rows))
        for i, (_doc_id, writers) in enumerate(fleets):
            assert eng.text(i) == writers[0].text, f"doc {i} diverged"
        assert not eng.errors().any()
    finally:
        fc.close()


def test_fleet_main_entry_cross_process(server):
    """The deployable fleet entry (deploy/compose.yaml fleet tier): spawn
    fleet_main as its OWN process against the TCP front; it consumes,
    applies on device, reports status JSON, and exits at the row bound."""
    import json
    import os
    import subprocess
    import sys

    writers = _writers(server, "dm", 2)
    a, _b = writers
    a.insert_text(0, "compose")
    rows = _flush(server, "dm", writers)

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu');"
         "from fluidframework_tpu.server.fleet_main import main;"
         f"raise SystemExit(main(['--port', '{server.port}',"
         f" '--docs', 'dm', '--exit-after-rows', '{rows}']))"],
        capture_output=True, text=True, timeout=180, env=dict(os.environ),
        cwd=repo_root,
    )
    assert out.returncode == 0, out.stderr[-500:]
    status = json.loads(out.stdout.strip().splitlines()[-1])
    assert status["done"] and status["errors"] == 0
    assert status["texts"]["dm"] == "compose"


def test_fleet_consumer_boots_from_scribe_summary(server, tmp_path):
    """Boot-from-summary through the REAL wire path: a scribe summarizes
    and acks the doc's sequenced prefix; a cold FleetConsumer seeds its
    engine from the acked commit, consumes the firehose from offset 0, and
    converges byte-identically — replaying only the post-ack tail."""
    from fluidframework_tpu.server.ordered_log import Topic
    from fluidframework_tpu.server.scribe import (
        ScribeConfig,
        ScribeLambda,
        SummaryRecordStore,
    )

    writers = _writers(server, "db", 2)
    a, b = writers
    a.insert_text(0, "hello scribe")
    _flush(server, "db", writers)
    b.remove_range(0, 6)
    _flush(server, "db", writers)

    # The scribe consumes the same total order (here: mirrored from the
    # doc's sequencer log into an op topic) and acks the prefix.
    topic = Topic("deltas", 1)
    with server.lock:
        for m in server.service.document("db").sequencer.log:
            topic.produce("db", m)
    scribe = ScribeLambda(topic, str(tmp_path / "scribe"),
                          config=ScribeConfig(max_ops=1))
    scribe.pump()
    acked_seq = scribe.refs["db"]["seq"]
    assert scribe.health()["summaries_written"] >= 1

    # Post-ack tail lands after the summary was acked.
    a.insert_text(len(a.text), "!")
    tail_rows = _flush(server, "db", writers)

    eng = DocBatchEngine(1, max_segments=256, text_capacity=4096,
                         max_insert_len=16, ops_per_step=8, use_mesh=False,
                         doc_keys=["db"])
    fc = FleetConsumer("127.0.0.1", server.port, eng, ["db"],
                       boot_store=SummaryRecordStore.from_scribe(scribe))
    try:
        assert fc.booted_docs == [0]
        assert eng.text(0) == "scribe"  # summary state alone, pre-catch-up
        fc.run_for(tail_rows)  # catch-up replays all; only the tail stages
        assert eng.text(0) == a.text == "scribe!"
        h = fc.health()
        assert h["checkpointed_ops_skipped"] > 0, "prefix not skipped"
        assert h["boot_replay_len"] == tail_rows
        assert h["booted_docs"] == 1
        assert eng.hosts[0].base_seq == acked_seq
        assert not eng.errors().any()
    finally:
        fc.close()
        scribe.close()


def test_fleet_consumer_reports_dead_sockets_on_shard_close():
    """The shard closing the firehose must surface as dead_socks (the
    supervisor-restart signal), never as a silent healthy-looking idle.
    Modeled with a minimal shard that closes right after the handshake —
    the socket state a dying shard PROCESS leaves behind."""
    import json as _json
    import socket as _socket

    lsock = _socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]

    def serve():
        conn, _ = lsock.accept()
        conn.recv(4096)  # the consume request
        conn.sendall(
            (_json.dumps({"t": "consuming", "doc": "dx"}) + "\n").encode()
        )
        conn.close()  # shard dies

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    eng = DocBatchEngine(1, max_segments=64, text_capacity=512,
                         max_insert_len=8, ops_per_step=4, use_mesh=False,
                         recovery="off")
    fc = FleetConsumer("127.0.0.1", port, eng, ["dx"])
    try:
        assert not fc.dead_socks
        for _ in range(100):
            fc.pump()
            if fc.dead_socks:
                break
        assert fc.dead_socks == {0}
    finally:
        fc.close()
        lsock.close()


def test_wire_to_device_mesh_served_fleet(server):
    """The production mesh path end to end: wire bytes off the firehose,
    native decode, placement-packed staging, shard_map megastep dispatch
    over the 8 virtual devices — every doc converges and the per-shard
    health surface is live (the ``fleet_main --mesh`` serving loop)."""
    from fluidframework_tpu.parallel.mesh import doc_mesh

    n_docs = 8
    fleets = [(f"m{i}", _writers(server, f"m{i}", 2)) for i in range(n_docs)]
    rows = [0] * n_docs
    rng = random.Random(11)
    for _ in range(3):
        for i, (doc_id, writers) in enumerate(fleets):
            for c in writers:
                n = len(c.text)
                if rng.random() < 0.7 or n < 4:
                    c.insert_text(rng.randint(0, n), "".join(
                        rng.choice("abcdef") for _ in range(rng.randint(1, 6))
                    ))
                else:
                    p = rng.randint(0, n - 2)
                    c.remove_range(p, p + 1)
            rows[i] += _flush(server, doc_id, writers)

    eng = DocBatchEngine(n_docs, max_segments=512, text_capacity=8192,
                         max_insert_len=8, ops_per_step=8, megastep_k=4,
                         mesh=doc_mesh(), spare_slots=8)
    fc = FleetConsumer("127.0.0.1", server.port,
                       eng, [doc_id for doc_id, _ in fleets])
    try:
        fc.run_for(sum(rows))
        for i, (doc_id, writers) in enumerate(fleets):
            assert eng.text(i) == writers[0].text, f"{doc_id} diverged"
        h = fc.health()
        assert h["n_shards"] == 8 and len(h["shard_ops"]) == 8
        assert h["megastep_dispatches"] >= 1
        # Live migration composes with the consumer: move a doc and keep
        # serving (placement is host-side; the socket set is untouched).
        src = eng.shard_of(0)
        dst = (src + 1) % eng.n_shards
        assert eng.migrate_doc(0, dst) and eng.shard_of(0) == dst
        for i, (doc_id, writers) in enumerate(fleets):
            writers[0].insert_text(0, "Z")
            rows[i] += _flush(server, doc_id, writers)
        fc.run_for(sum(rows))
        for i, (doc_id, writers) in enumerate(fleets):
            assert eng.text(i) == writers[0].text, f"{doc_id} post-move"
    finally:
        fc.close()
