"""SharedMap: LWW convergence, pending overlay, and kernel equivalence."""

import random

import numpy as np
import pytest

from fluidframework_tpu.dds.shared_map import SharedMap
from fluidframework_tpu.ops import map_kernel as mpk
from fluidframework_tpu.server.local_service import LocalDocument

import jax.numpy as jnp


def make_maps(doc, n):
    maps = []
    for i in range(n):
        m = SharedMap(client_id=f"c{i}")
        doc.connect(m.client_id, m.process)
        maps.append(m)
    doc.process_all()
    return maps


def pump(doc, maps):
    moved = True
    while moved:
        moved = False
        for m in maps:
            for msg in m.take_outbox():
                doc.submit(msg)
                moved = True
        if doc.pending_count:
            doc.process_all()
            moved = True


class TestSharedMap:
    def test_lww_by_sequence_order(self):
        doc = LocalDocument("d")
        a, b = make_maps(doc, 2)
        a.set("k", 1)
        b.set("k", 2)  # sequenced later -> wins
        pump(doc, [a, b])
        assert a.sequenced == b.sequenced == {"k": 2}

    def test_pending_masks_remote(self):
        doc = LocalDocument("d")
        a, b = make_maps(doc, 2)
        b.set("k", "remote")
        for m in b.take_outbox():
            doc.submit(m)
        a.set("k", "local")  # pending on a
        doc.process_all()  # delivers b's set while a's is pending
        assert a.get("k") == "local"  # pending set masks the remote value
        pump(doc, [a, b])
        assert a.get("k") == b.get("k") == "local"  # a's op sequenced later

    def test_clear_vs_concurrent_set(self):
        doc = LocalDocument("d")
        a, b = make_maps(doc, 2)
        a.set("x", 1)
        a.set("y", 2)
        pump(doc, [a, b])
        a.clear()
        b.set("x", 99)  # sequenced after the clear -> survives
        pump(doc, [a, b])
        assert a.items() == b.items() == {"x": 99}

    def test_delete_pending_overlay(self):
        doc = LocalDocument("d")
        (a,) = make_maps(doc, 1)
        a.set("k", 1)
        pump(doc, [a])
        a.delete("k")
        assert a.get("k") is None  # optimistic delete
        assert "k" not in a.keys()
        pump(doc, [a])
        assert a.sequenced == {}


@pytest.mark.parametrize("seed", range(10))
def test_map_farm_and_kernel_equivalence(seed):
    """Random concurrent set/delete/clear; all replicas converge, and the
    TPU batch kernel replaying the sequenced log matches exactly."""
    rng = random.Random(seed)
    doc = LocalDocument("d")
    maps = make_maps(doc, rng.randint(2, 4))
    keyspace = [f"k{i}" for i in range(8)]
    for _round in range(rng.randint(3, 8)):
        for m in maps:
            for _ in range(rng.randint(0, 3)):
                r = rng.random()
                if r < 0.70:
                    m.set(rng.choice(keyspace), rng.randint(0, 100))
                elif r < 0.92:
                    m.delete(rng.choice(keyspace))
                else:
                    m.clear()
            if rng.random() < 0.7:
                for msg in m.take_outbox():
                    doc.submit(msg)
        doc.process_some(rng.randint(0, doc.pending_count))
    pump(doc, maps)
    states = {tuple(sorted(m.sequenced.items())) for m in maps}
    assert len(states) == 1

    # Kernel replay: intern keys/values, apply the op log in random batch
    # sizes, compare the final present-set.
    key_intern = {k: i for i, k in enumerate(keyspace)}
    ops = []
    for msg in doc.sequencer.log:
        if msg.type != "op":
            continue
        c = msg.contents
        if c["type"] == "set":
            ops.append((mpk.MapOpKind.SET, key_intern[c["key"]], c["value"], msg.seq))
        elif c["type"] == "delete":
            ops.append((mpk.MapOpKind.DELETE, key_intern[c["key"]], 0, msg.seq))
        else:
            ops.append((mpk.MapOpKind.CLEAR, -1, 0, msg.seq))
    state = mpk.init_state(max_keys=len(keyspace))
    i = 0
    while i < len(ops):
        n = rng.randint(1, 6)
        chunk = ops[i : i + n]
        i += n
        arr = np.array(chunk, np.int32).reshape(-1, 4)
        state = mpk.apply_batch(
            state,
            jnp.asarray(arr[:, 0]),
            jnp.asarray(arr[:, 1]),
            jnp.asarray(arr[:, 2]),
            jnp.asarray(arr[:, 3]),
        )
    got = mpk.host_items(state)
    expected = {key_intern[k]: v for k, v in maps[0].sequenced.items()}
    assert got == expected
