"""simple-tree typed public API (ref tree/src/simple-tree/).

SchemaFactory-declared schemas, typed reads/writes over live paths,
implicit plain-data construction, identity-preserving array moves, the
Tree helper namespace, node events, and the schematize gate — driven
through real two-client collaboration over the sequencer.
"""

from __future__ import annotations

import pytest

from fluidframework_tpu.dds.channels import default_registry
from fluidframework_tpu.dds.tree import (
    SchemaFactory,
    Tree,
    TreeViewConfiguration,
    optional,
)
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.local_service import LocalService


def host(n_clients: int = 1):
    svc = LocalService()
    doc = svc.document("d")
    rts = []
    for i in range(n_clients):
        rt = ContainerRuntime(default_registry(), container_id=f"c{i}")
        rt.create_datastore("root").create_channel("sharedTree", "t")
        rt.connect(doc, f"c{i}")
        rts.append(rt)
    doc.process_all()
    chans = [rt.datastore("root").get_channel("t") for rt in rts]

    def settle():
        for rt in rts:
            rt.flush()
        doc.process_all()

    return chans, settle


def make_app_schema():
    sf = SchemaFactory("com.example.todo")
    Item = sf.object(
        "Item", title=sf.string, done=sf.boolean, priority=optional(sf.number)
    )
    Items = sf.array("Items", Item)
    List_ = sf.object("List", name=sf.string, items=Items)
    return sf, Item, Items, List_


def test_declarative_authoring_end_to_end():
    chans, settle = host(2)
    a, b = chans
    sf, Item, Items, List_ = make_app_schema()

    va = a.typed_view(TreeViewConfiguration(List_))
    va.initialize(List_(
        name="groceries",
        items=Items([Item(title="milk", done=False)]),
    ))
    settle()

    # The second client views with an equivalently-declared schema.
    _sf2, _i2, _is2, List2 = make_app_schema()
    vb = b.typed_view(TreeViewConfiguration(List2))
    assert vb.compatibility.can_view and vb.compatibility.is_equivalent

    root_b = vb.root
    assert root_b.name == "groceries"
    assert len(root_b.items) == 1
    assert root_b.items[0].title == "milk"
    assert root_b.items[0].done is False
    assert root_b.items[0].priority is None

    # Typed writes from both sides converge.
    va.root.items[0].done = True
    root_b.items.insert_at_end(Item(title="eggs", done=False, priority=2))
    settle()
    for v in (va, vb):
        items = v.root.items
        assert [i.title for i in items] == ["milk", "eggs"]
        assert items[0].done is True
        assert items[1].priority == 2


def test_plain_data_implicit_construction():
    chans, settle = host(1)
    (a,) = chans
    _sf, _Item, _Items, List_ = make_app_schema()
    v = a.typed_view(TreeViewConfiguration(List_))
    # Dicts/lists hydrate through the schema (ref insertable content).
    v.initialize({
        "name": "trip",
        "items": [{"title": "pack", "done": False}],
    })
    settle()
    assert v.root.name == "trip"
    assert v.root.items[0].title == "pack"
    v.root.items.insert_at_end({"title": "drive", "done": False})
    assert [i.title for i in v.root.items] == ["pack", "drive"]


def test_required_field_enforced_at_construction():
    _sf, Item, _Items, _List = make_app_schema()
    with pytest.raises(TypeError, match="missing required field"):
        Item(title="x")  # done missing
    with pytest.raises(TypeError, match="unknown fields"):
        Item(title="x", done=True, color="red")


def test_array_moves_preserve_identity_under_concurrency():
    """move_to_index is a real move: a concurrent value edit on the moved
    node lands on it at its new position (remove+insert would lose it)."""
    chans, settle = host(2)
    a, b = chans
    sf = SchemaFactory("m")
    Row = sf.object("Row", v=sf.number)
    Rows = sf.array("Rows", Row)
    va = a.typed_view(TreeViewConfiguration(Rows))
    va.initialize([Row(v=1), Row(v=2), Row(v=3)])
    settle()
    vb = b.typed_view(TreeViewConfiguration(Rows))

    # a moves row 0 to the end while b concurrently edits row 0's value.
    va.root.move_to_end(0)
    vb.root[0].v = 99
    settle()
    for v in (va, vb):
        assert [r.v for r in v.root] == [2, 3, 99]


def test_tree_helpers_and_status():
    chans, settle = host(1)
    (a,) = chans
    _sf, Item, Items, List_ = make_app_schema()
    v = a.typed_view(TreeViewConfiguration(List_))
    v.initialize(List_(name="n", items=Items([Item(title="t", done=False)])))
    settle()
    root = v.root
    item = root.items[0]
    assert Tree.is_(root, List_) and Tree.is_(item, Item)
    assert Tree.schema(item) is Item
    assert Tree.key(item) == 0            # index within the array
    assert Tree.key(root.items) == "items"
    assert Tree.key(root) == 0            # root-field position
    assert Tree.parent(root) is None
    arr = Tree.parent(item)
    assert Tree.is_(Tree.parent(arr), List_)
    assert Tree.status(item) == "inDocument"
    root.items.remove_at(0)
    assert Tree.status(item) == "removed"


def test_node_events_fire_on_local_and_remote_changes():
    chans, settle = host(2)
    a, b = chans
    sf = SchemaFactory("e")
    Box = sf.object("Box", n=sf.number)
    Boxes = sf.array("Boxes", Box)
    va = a.typed_view(TreeViewConfiguration(Boxes))
    va.initialize([Box(n=1), Box(n=2)])
    settle()
    vb = b.typed_view(TreeViewConfiguration(Boxes))

    node_hits, tree_hits = [], []
    un1 = Tree.on(vb.root[0], "nodeChanged", lambda: node_hits.append(1))
    un2 = Tree.on(vb.root, "treeChanged", lambda: tree_hits.append(1))

    va.root[0].n = 5          # remote (from b's perspective) node change
    settle()
    assert node_hits and tree_hits
    n_node = len(node_hits)
    va.root[1].n = 7          # sibling change: subtree yes, node no
    settle()
    assert len(node_hits) == n_node
    assert len(tree_hits) > 1
    un1()
    un2()
    va.root[0].n = 9
    settle()
    assert len(node_hits) == n_node  # unsubscribed


def test_schematize_gate_blocks_incompatible_views():
    chans, settle = host(2)
    a, b = chans
    sf = SchemaFactory("g")
    Point = sf.object("Point", x=sf.number)
    Points = sf.array("Points", Point)
    va = a.typed_view(TreeViewConfiguration(Points))
    va.initialize([Point(x=1)])
    settle()

    sf2 = SchemaFactory("g")
    Other = sf2.object("Other", y=sf2.string)
    Others = sf2.array("Others", Other)
    vb = b.typed_view(TreeViewConfiguration(Others))
    assert not vb.compatibility.can_view
    with pytest.raises(RuntimeError, match="cannot read"):
        _ = vb.root
    with pytest.raises(RuntimeError, match="cannot upgrade"):
        vb.upgrade_schema()

    # A WIDENED schema can upgrade but not view pre-upgrade (ref
    # SchemaCompatibilityStatus canUpgrade without canView).
    sf3 = SchemaFactory("g")
    P3 = sf3.object("Point", x=sf3.number, label=optional(sf3.string))
    Ps3 = sf3.array("Points", P3)
    vc = b.typed_view(TreeViewConfiguration(Ps3))
    assert vc.compatibility.can_upgrade and not vc.compatibility.can_view
    vc.upgrade_schema()
    settle()
    assert vc.compatibility.can_view
    vc.root[0].label = "origin"
    settle()
    assert vc.root[0].label == "origin"


def test_optional_field_clear_and_set():
    chans, settle = host(1)
    (a,) = chans
    _sf, Item, Items, List_ = make_app_schema()
    v = a.typed_view(TreeViewConfiguration(List_))
    v.initialize(List_(name="n", items=Items([Item(title="t", done=False)])))
    settle()
    item = v.root.items[0]
    item.priority = 3
    assert item.priority == 3
    item.priority = None  # optional clears
    assert item.priority is None
    with pytest.raises(ValueError, match="required field"):
        item.title = None


def test_handles_are_identity_stable_across_sibling_edits():
    """A handle follows ITS node when siblings are removed/moved — never
    silently rebinding to whatever sits at the old coordinates (ref
    treeNodeKernel anchors)."""
    chans, settle = host(1)
    (a,) = chans
    sf = SchemaFactory("i")
    Row = sf.object("Row", v=sf.number)
    Rows = sf.array("Rows", Row)
    v = a.typed_view(TreeViewConfiguration(Rows))
    v.initialize([Row(v=10), Row(v=20), Row(v=30)])
    settle()
    second = v.root[1]
    v.root.remove_at(0)        # sibling BEFORE the handle vanishes
    assert Tree.status(second) == "inDocument"
    assert second.v == 20      # still the same node, now at index 0
    assert Tree.key(second) == 0
    v.root.move_to_end(0)      # move it; handle follows
    assert second.v == 20 and Tree.key(second) == 1
    v.root.remove_at(1)        # now remove IT
    assert Tree.status(second) == "removed"


def test_failed_required_clear_leaves_no_edit():
    chans, settle = host(1)
    (a,) = chans
    _sf, Item, Items, List_ = make_app_schema()
    v = a.typed_view(TreeViewConfiguration(List_))
    v.initialize(List_(name="n", items=Items([Item(title="t", done=False)])))
    settle()
    before = v.root.to_json()
    with pytest.raises(ValueError):
        v.root.items[0].title = None
    assert v.root.to_json() == before  # no partial removal leaked


def test_concurrent_typed_replace_keeps_single_child():
    """Whole-content replace of a value/optional field rides the OPTIONAL
    field kind: two concurrent typed replaces converge to ONE child
    (later-sequenced wins) — a remove+insert pair would double-insert."""
    chans, settle = host(2)
    a, b = chans
    sf = SchemaFactory("app")
    Point = sf.object("Point", x=sf.number, y=sf.number)
    Doc = sf.object("Doc", pt=Point, label=optional(sf.string))
    cfg = TreeViewConfiguration(Doc)
    va = a.typed_view(cfg)
    vb = b.typed_view(cfg)
    va.initialize(Doc(pt=Point(x=1, y=2)))
    settle()
    # Race two whole-node replaces of the required field (settle flushes
    # client a first, so b's replace sequences later and wins).
    va.root.pt = Point(x=10, y=10)
    vb.root.pt = Point(x=20, y=20)
    settle()
    for t in (a, b):
        kids = t.forest.root_field[0].fields["pt"]
        assert len(kids) == 1, [k.to_json() for k in kids]
    assert va.root.pt.x == vb.root.pt.x == 20  # later wins
    # Optional field: concurrent set vs clear converges too.
    va.root.label = "a"
    settle()
    va.root.label = "b"
    vb.root.label = None
    settle()
    assert va.root.label is None and vb.root.label is None
    assert a.forest.equal(b.forest)


def test_replace_races_nested_edit_without_crashing():
    """Whole-field replace (OptionalChange) vs a nested leaf edit
    descending THROUGH the same field: ancestor path steps wrap by field
    kind, so both sides meet under one rebaser and converge (this raced a
    kind-mismatch assert before the kind-aware wrapper)."""
    chans, settle = host(2)
    a, b = chans
    sf = SchemaFactory("app2")
    Point = sf.object("Point", x=sf.number, y=sf.number)
    Doc = sf.object("Doc", pt=Point)
    cfg = TreeViewConfiguration(Doc)
    va, vb = a.typed_view(cfg), b.typed_view(cfg)
    va.initialize(Doc(pt=Point(x=1, y=2)))
    settle()
    va.root.pt = Point(x=10, y=10)   # whole-field replace
    vb.root.pt.x = 99                # nested edit through the same field
    settle()
    assert a.forest.equal(b.forest)
    kids = a.forest.root_field[0].fields["pt"]
    assert len(kids) == 1
    # The replace sequenced later (settle flushes a then b... a first):
    # b's nested edit lands on the OLD node, then a's replace? No — a
    # flushed first, so the replace is EARLIER and b's nested edit of the
    # replaced node drops: the replaced content stands.
    assert va.root.pt.x == vb.root.pt.x == 10
    assert va.root.pt.y == 10


def test_mixed_typed_untyped_producers_degrade_deterministically():
    """An untyped writer (sequence marks via raw make_* builders) racing a
    typed replace (OptionalChange) on ONE field: the kind mismatch
    resolves deterministically (later side drops) on every replica — no
    crash, identical forests."""
    from fluidframework_tpu.dds.tree.changeset import make_insert as mi

    chans, settle = host(2)
    a, b = chans
    sf = SchemaFactory("app3")
    Point = sf.object("Point", x=sf.number)
    Doc = sf.object("Doc", pt=Point)
    va = a.typed_view(TreeViewConfiguration(Doc))
    va.initialize(Doc(pt=Point(x=1)))
    settle()
    va.root.pt = Point(x=5)                     # optional-kind replace
    b.submit_change(mi([("", 0)], "pt", 1, [    # raw sequence insert
        __import__("fluidframework_tpu.dds.tree.schema",
                   fromlist=["leaf"]).leaf(42)
    ]))
    settle()
    assert a.forest.equal(b.forest)
