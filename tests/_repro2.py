import pathlib, sys
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
from fluidframework_tpu.dds.shared_string import SharedString
from fluidframework_tpu.server.local_service import LocalDocument
from test_mergetree_oracle import issue_op, pump
EVENTS = [
    ("op", 2, ("insert", 0, "hdhc")),
    ("op", 2, ("insert", 3, "ggbf")),
    ("op", 2, ("insert", 2, "bda")),
    ("op", 0, ("insert", 0, "ae")),
    ("op", 0, ("insert", 1, "hffa")),
    ("op", 2, ("insert", 9, "afg")),
    ("flush", 2),
    ("deliver", 2),
    ("op", 0, ("obliterate_sided", (0, True), (4, False))),
    ("flush", 0),
    ("op", 2, ("obliterate", 1, 6)),
    ("flush", 2),
    ("op", 0, ("obliterate_sided", (1, True), (5, False))),
    ("deliver", 5),
    ("op", 0, ("insert", 3, "ed")),
]
doc = LocalDocument("d")
clients = [SharedString(client_id=f"c{i}") for i in range(3)]
for c in clients:
    doc.connect(c.client_id, c.process)
doc.process_all()
for ev in EVENTS:
    if ev[0] == "op":
        issue_op(clients[ev[1]], ev[2])
    elif ev[0] == "flush":
        for m in clients[ev[1]].take_outbox():
            doc.submit(m)
    else:
        doc.process_some(min(ev[1], doc.pending_count))
pump(doc, clients)
for c in clients[:2]:
    print(c.client_id, repr(c.text))
    for s in c.backend.segments:
        print(f"   {s.text!r:8} ins=({s.ins_key},{s.ins_client}) rem={s.removes} obpre={None if s.ob_preceding is None else s.ob_preceding.key}")
