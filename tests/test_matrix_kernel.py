"""Differential: matrix TPU kernel vs host SharedMatrix oracle."""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from fluidframework_tpu.dds.shared_matrix import SharedMatrix
from fluidframework_tpu.ops import matrix_kernel as mxk
from fluidframework_tpu.server.local_service import LocalDocument

from test_shared_matrix import make_matrices, pump


def replay_through_kernel(doc: LocalDocument, value_intern):
    """Encode the sequenced op log into kernel ops and apply in one batch."""
    quorum = {}
    ops = []
    for msg in doc.sequencer.log:
        if msg.type == "join":
            quorum[msg.contents["clientId"]] = msg.contents["short"]
            continue
        if msg.type != "op":
            continue
        c = msg.contents
        client = quorum[msg.client_id]
        kindmap = {
            "insertRows": mxk.MatrixOpKind.INSERT_ROWS,
            "insertCols": mxk.MatrixOpKind.INSERT_COLS,
            "removeRows": mxk.MatrixOpKind.REMOVE_ROWS,
            "removeCols": mxk.MatrixOpKind.REMOVE_COLS,
        }
        if c["type"] in kindmap:
            ops.append(
                [kindmap[c["type"]], msg.seq, client, msg.ref_seq,
                 c["pos"], c["count"], 0, 0]
            )
        elif c["type"] == "set":
            ops.append(
                [mxk.MatrixOpKind.SET_CELL, msg.seq, client, msg.ref_seq,
                 c["row"], c["col"], value_intern(c["value"]),
                 1 if c.get("fwwMode") else 0]
            )
    state = mxk.init_state(max_rows=64, max_cols=64, max_segments=128)
    if ops:
        state = mxk.apply_ops(state, jnp.asarray(np.array(ops, np.int32)))
    return state


@pytest.mark.parametrize("seed", range(6))
def test_matrix_kernel_matches_oracle(seed):
    rng = random.Random(seed)
    doc = LocalDocument("d")
    ms = make_matrices(doc, rng.randint(2, 3))
    for _round in range(rng.randint(3, 6)):
        for m in ms:
            for _ in range(rng.randint(0, 3)):
                r = rng.random()
                nrows = len(m.rows.handles(2**30 - 1, m.short_client))
                ncols = len(m.cols.handles(2**30 - 1, m.short_client))
                if r < 0.3 or nrows == 0:
                    m.insert_rows(rng.randint(0, nrows), rng.randint(1, 2))
                elif r < 0.5 or ncols == 0:
                    m.insert_cols(rng.randint(0, ncols), rng.randint(1, 2))
                elif r < 0.58 and nrows > 1:
                    m.remove_rows(rng.randint(0, nrows - 1), 1)
                elif r < 0.64 and ncols > 1:
                    m.remove_cols(rng.randint(0, ncols - 1), 1)
                elif ncols > 0 and nrows > 0:
                    m.set_cell(
                        rng.randint(0, nrows - 1), rng.randint(0, ncols - 1),
                        rng.randint(1, 999),
                    )
            if rng.random() < 0.7:
                for msg in m.take_outbox():
                    doc.submit(msg)
        doc.process_some(rng.randint(0, doc.pending_count))
    pump(doc, ms)

    state = replay_through_kernel(doc, value_intern=lambda v: int(v))
    assert int(state.error) == 0
    kernel_grid = mxk.to_grid(state)
    oracle_grid = ms[0].to_grid()
    # Handles differ between implementations only if allocation order
    # diverged; grids must be identical cell-for-cell.
    assert kernel_grid == oracle_grid, f"seed {seed} diverged"


def test_fww_kernel_semantics():
    doc = LocalDocument("d")
    a, b = make_matrices(doc, 2)
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    pump(doc, [a, b])
    a.switch_to_fww()
    b.switch_to_fww()
    a.set_cell(0, 0, 7)
    b.set_cell(0, 0, 8)  # concurrent loser under FWW
    pump(doc, [a, b])
    state = replay_through_kernel(doc, value_intern=lambda v: int(v))
    assert mxk.to_grid(state) == a.to_grid() == [[7]]
